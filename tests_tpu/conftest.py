"""On-TPU Pallas parity suite (VERDICT r2 missing #3).

Runs on the real chip (`python -m pytest tests_tpu -q`) — unlike tests/,
which pins the 8-device CPU simulator, this conftest leaves the default
backend (the axon-tunneled TPU) in place and skips everything when no TPU
is present. FLAGS_pallas_strict=1 for the whole suite: a kernel that falls
back to XLA is a FAILURE here, not a silent pass.

Reference discipline: the OpTest pattern (SURVEY.md §4) — every Pallas
kernel checked against its XLA twin, forward and backward, on hardware.
"""

import jax
import pytest


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def pytest_collection_modifyitems(config, items):
    if not _on_tpu():
        skip = pytest.mark.skip(reason="no TPU backend — parity suite "
                                "requires the real chip")
        for it in items:
            it.add_marker(skip)


@pytest.fixture(autouse=True)
def _strict_pallas():
    from paddle_tpu.core.flags import get_flags, set_flags
    prior = get_flags(["FLAGS_pallas_strict", "FLAGS_use_pallas_kernels"])
    set_flags({"FLAGS_pallas_strict": True, "FLAGS_use_pallas_kernels": True})
    import paddle_tpu
    paddle_tpu.seed(0)
    yield
    set_flags(prior)
