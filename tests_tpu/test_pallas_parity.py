"""Pallas-vs-XLA numeric parity on the real TPU, strict mode.

Covers every Pallas kernel in paddle_tpu/ops: flash attention (forward,
backward, LSE variant, GQA), the fused decode-step kernel, and the rms_norm
kernel kept for benchmarking. CPU CI never executes these paths
(use_pallas() is False off-TPU); this suite is the hardware leg of the
reference's OpTest discipline (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.nn import functional as F
from paddle_tpu.ops import flash_attention as fa


def rand(key, *shape, dtype=jnp.bfloat16, scale=0.5):
    return (jax.random.normal(jax.random.PRNGKey(key), shape) * scale).astype(
        dtype)


def assert_close(a, b, rtol=2e-2, atol=2e-2, frac=0.995):
    """bf16-tolerant: allclose on >=99.5% of entries, tight on the mean."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    ok = np.isclose(a, b, rtol=rtol, atol=atol).mean()
    assert ok >= frac, f"only {ok:.4f} of entries close"
    assert np.abs(a - b).mean() < atol, np.abs(a - b).mean()


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("nkv", [8, 2])   # MHA / GQA
def test_flash_forward_parity(causal, nkv):
    b, s, h, d = 2, 1024, 8, 64
    q = rand(0, b, s, h, d)
    k = rand(1, b, s, nkv, d)
    v = rand(2, b, s, nkv, d)
    pal = fa._flash_attention_pallas(q, k, v, causal, None)
    ref = fa._xla_attention(q, k, v, is_causal=causal)
    assert_close(pal, ref)


def test_flash_backward_parity():
    b, s, h, d = 2, 1024, 4, 64
    q = rand(3, b, s, h, d)
    k = rand(4, b, s, h, d)
    v = rand(5, b, s, h, d)

    def pal_loss(q, k, v):
        return jnp.sum(fa._flash_attention_vjp(q, k, v, True, None)
                       .astype(jnp.float32) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(fa._xla_attention(q, k, v, is_causal=True)
                       .astype(jnp.float32) ** 2)

    gp = jax.jit(jax.grad(pal_loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(gp, gr):
        assert_close(a, b_, rtol=5e-2, atol=5e-2)


def test_flash_lse_parity():
    b, s, h, d = 2, 1024, 4, 64
    q = rand(6, b, s, h, d)
    k = rand(7, b, s, h, d)
    v = rand(8, b, s, h, d)
    out_p, lse_p = fa._flash_fwd(q, k, v, True, None)
    out_r, lse_r = fa._xla_fwd_lse(q, k, v, True, None)
    assert_close(out_p, out_r)
    assert_close(lse_p[..., 0], lse_r, rtol=1e-2, atol=1e-2)


def test_sdpa_dispatches_pallas_on_tpu():
    """The public API path must actually take the kernel (strict mode would
    raise on kernel failure; this guards the dispatch predicate)."""
    b, s, h, d = 2, 1024, 4, 64
    q = rand(9, b, s, h, d)
    k = rand(10, b, s, h, d)
    v = rand(11, b, s, h, d)
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    ref = fa._xla_attention(q, k, v, is_causal=True)
    assert_close(out, ref)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_cross_attention_parity(causal):
    """sq != sk (the UNet cross-attn shape), bottom-right causal."""
    b, h, d = 2, 4, 64
    q = rand(20, b, 1024, h, d)
    k = rand(21, b, 256, h, d)
    v = rand(22, b, 256, h, d)
    pal = fa._flash_call(q, k, v, causal, None, None, None, None)
    ref = fa._xla_attention(q, k, v, is_causal=causal)
    assert_close(pal, ref)


def test_flash_kv_lens_and_segments_parity():
    """Structured masks (padding lengths + packed segments), fwd + bwd,
    including fully-masked rows (out 0, grads 0 — both paths)."""
    b, h, d, s = 2, 4, 64, 1024
    q = rand(23, b, s, h, d)
    k = rand(24, b, s, h, d)
    v = rand(25, b, s, h, d)
    lens = jnp.asarray([700, 1024])
    seg = jnp.asarray(np.repeat(np.arange(8), 128)[None].repeat(b, 0))

    pal = fa._flash_call(q, k, v, True, None, lens, seg, seg)
    ref = fa._xla_attention(q, k, v, is_causal=True, kv_lens=lens,
                            seg_q=seg, seg_k=seg)
    assert_close(pal, ref)

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2)

    gp = jax.jit(jax.grad(loss(lambda *a: fa._flash_call(
        *a, True, None, lens, seg, seg)), argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss(lambda *a: fa._xla_attention(
        *a, is_causal=True, kv_lens=lens, seg_q=seg, seg_k=seg)),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(gp, gr):
        assert_close(a, b_, rtol=5e-2, atol=5e-2)


def test_flash_public_api_structured_masks():
    """The public sdpa args dispatch to the kernel in strict mode."""
    from paddle_tpu.nn import functional as F
    b, h, d, s = 2, 4, 64, 1024
    q = rand(26, b, s, h, d)
    k = rand(27, b, s, h, d)
    v = rand(28, b, s, h, d)
    lens = jnp.asarray([512, 1024])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                         kv_lens=lens)
    ref = fa._xla_attention(q, k, v, is_causal=True, kv_lens=lens)
    assert_close(out, ref)


@pytest.mark.parametrize("kind", ["bool", "float"])
def test_flash_dense_mask_parity(kind):
    """Arbitrary dense attn_mask tiles (round 5 — the last mask-surface
    gap): kernel fwd+bwd == XLA reference under a random (b, 1, s, s)
    mask, bool and additive-float forms."""
    from paddle_tpu.ops import flash_attention as fa

    r = np.random.RandomState(0)
    b, s, h, d = 2, 512, 2, 64
    q, k, v = (jnp.asarray(r.standard_normal((b, s, h, d)) * 0.3,
                           jnp.float32) for _ in range(3))
    mb = r.rand(b, 1, s, s) > 0.3
    mb[:, :, :, 0] = True            # no fully-masked rows
    if kind == "bool":
        mask_x = jnp.asarray(mb)
        mask_k = mask_x.astype(jnp.int8)
    else:
        mask_x = jnp.asarray(np.where(mb, r.standard_normal(
            (b, 1, s, s)) * 0.5, -1e30), jnp.float32)
        mask_k = mask_x

    def loss_k(q, k, v):
        return fa._flash_call(q, k, v, False, None, None, None, None,
                              mask=mask_k).astype(jnp.float32).sum()

    def loss_x(q, k, v):
        return fa._xla_attention(q, k, v, attn_mask=mask_x,
                                 is_causal=False).astype(
            jnp.float32).sum()

    ok, gk = jax.value_and_grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    ox, gx = jax.value_and_grad(loss_x, argnums=(0, 1, 2))(q, k, v)
    assert np.allclose(float(ok), float(ox), rtol=2e-3)
    for a, b_ in zip(gk, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-2, atol=5e-3)


def test_flash_dense_mask_block_skipping():
    """A mask whose valid region covers only the first quarter of the
    keys must produce identical results to the unskipped dense form —
    the prefix/suffix block-skipping bounds are exact."""
    from paddle_tpu.ops import flash_attention as fa

    r = np.random.RandomState(1)
    b, s, h, d = 1, 512, 2, 64
    q, k, v = (jnp.asarray(r.standard_normal((b, s, h, d)) * 0.3,
                           jnp.float32) for _ in range(3))
    mask = np.zeros((1, 1, s, s), bool)
    mask[:, :, :, :128] = True       # only k-block 0 valid
    out = fa._flash_call(q, k, v, False, None, None, None, None,
                         mask=jnp.asarray(mask, jnp.int8))
    ref = fa._xla_attention(q, k, v, attn_mask=jnp.asarray(mask),
                            is_causal=False)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-3)


def test_flash_dropout_in_kernel():
    """In-kernel attention dropout (round 5 — the last kernel-surface
    gap): deterministic per seed, unbiased vs the no-dropout output, and
    the backward regenerates the forward's mask (finite-difference check
    through the kernel with a pinned seed)."""
    import paddle_tpu
    from paddle_tpu.ops import flash_attention as fa

    r = np.random.RandomState(0)
    b, s, h, d = 2, 512, 4, 64
    q, k, v = (jnp.asarray(r.standard_normal((b, s, h, d)) * 0.3,
                           jnp.float32) for _ in range(3))
    p = 0.3

    def run(seed_int, dropout=p):
        paddle_tpu.seed(seed_int)      # pins the kernel's dropout seed
        return fa._flash_call(q, k, v, True, None, None, None, None,
                              dropout_p=dropout)

    o1 = np.asarray(run(7), np.float32)
    o2 = np.asarray(run(7), np.float32)
    np.testing.assert_array_equal(o1, o2)          # deterministic
    o3 = np.asarray(run(8), np.float32)
    assert np.abs(o1 - o3).max() > 1e-4            # seed matters
    base = np.asarray(run(7, dropout=0.0), np.float32)
    # unbiased: averaging over many seeds approaches the no-drop output
    acc = np.zeros_like(base)
    n_seeds = 24
    for sd in range(n_seeds):
        acc += np.asarray(run(100 + sd), np.float32)
    err = np.abs(acc / n_seeds - base).mean() / (np.abs(base).mean())
    assert err < 0.15, err

    # backward consistency. Pointwise FD on dq is hopeless here: the
    # projected-loss reduction carries ~1e-3 of f32 noise while dq
    # signals are ~1e-4 (measured; the formula itself is verified
    # against autodiff with an explicit mask in the numpy twin). Three
    # checks that ARE decisive:
    proj = jnp.asarray(r.standard_normal((b, s, h, d)), jnp.float32)

    def loss_of(qq, vv, p_, seed_int=7):
        paddle_tpu.seed(seed_int)
        out = fa._flash_call(qq, k, vv, True, None, None, None, None,
                             dropout_p=p_)
        return (out * proj).astype(jnp.float32).sum()

    # (a) p -> 0 limit: the dropout backward must reduce EXACTLY to the
    # no-dropout backward (threshold saturates to keep-all)
    g_p0 = np.asarray(jax.grad(lambda qq: loss_of(qq, v, 0.0))(q))
    g_eps = np.asarray(jax.grad(lambda qq: loss_of(qq, v, 1e-9))(q))
    np.testing.assert_array_equal(g_p0, g_eps)

    # (b) dv finite difference — dv entries are O(1), far above the
    # noise floor; a mask mismatch between the fwd and dkv kernels
    # would break this immediately
    gv = np.asarray(jax.grad(lambda vv: loss_of(q, vv, p))(v))
    for idx in [(0, 3, 1, 5), (1, 100, 2, 17)]:
        fd = (float(loss_of(q, v.at[idx].add(1e-2), p))
              - float(loss_of(q, v.at[idx].add(-1e-2), p))) / 2e-2
        assert abs(fd - gv[idx]) < 0.05 * max(0.2, abs(fd)), (idx, fd,
                                                              gv[idx])

    # (c) gradient unbiasedness: dq averaged over seeds approaches the
    # p=0 gradient (a wrong mask in the dq kernel cannot average out)
    gacc = np.zeros_like(g_p0)
    for sd in range(n_seeds):
        gacc += np.asarray(jax.grad(
            lambda qq: loss_of(qq, v, p, 100 + sd))(q))
    gmean = gacc / n_seeds
    denom = np.abs(g_p0).mean()
    assert np.abs(gmean - g_p0).mean() / denom < 0.25, \
        np.abs(gmean - g_p0).mean() / denom


# ---------------------------------------------------------------------------
# fused decode step
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("nkv,rep", [(4, 1), (2, 2)])
def test_fused_decode_kernel_parity(nkv, rep):
    from paddle_tpu.ops import fused_decode as fd
    from paddle_tpu.ops.rope import rope_cos_sin

    L, b, S, hd, h, ffn = 3, 8, 256, 64, 256, 512
    nh = nkv * rep
    if nkv * hd % 128:
        pytest.skip("dkv not a lane multiple")
    r = np.random.RandomState(0)
    f = lambda *s: jnp.asarray(r.randn(*s) * 0.05, jnp.bfloat16)
    params = {"ln1": jnp.ones((L, h), jnp.bfloat16),
              "wqkv": f(L, h, (nh + 2 * nkv) * hd),
              "wo": f(L, nh * hd, h), "ln2": jnp.ones((L, h), jnp.bfloat16),
              "wg": f(L, h, ffn), "wu": f(L, h, ffn), "wd": f(L, ffn, h)}
    x = f(b, h)
    kv = f(L, b, S, 2 * nkv * hd)
    pos = 130
    cos, sin = rope_cos_sin(S, hd)

    xr, kvr = jax.jit(lambda *a: fd.fused_decode_reference(
        *a, num_heads=nh, num_kv_heads=nkv, eps=1e-5))(
        x, params, kv, pos, cos[pos:pos + 1], sin[pos:pos + 1])
    xp, kvp = jax.jit(lambda x, p, kv: fd._fused_decode_pallas(
        x, p, kv, pos, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        eps=1e-5))(x, params, kv)

    assert_close(xp, xr)
    # cache: identical except bf16-ulp noise at the written token
    d = np.abs(np.asarray(kvr, np.float32) - np.asarray(kvp, np.float32))
    touched = sorted(set(np.argwhere(d > 1e-3)[:, 2].tolist()))
    assert touched in ([], [pos]), touched
    assert d.max() < 0.05, d.max()


@pytest.mark.parametrize("int8", [False, True])
def test_fused_decode_qsplit_parity(int8):
    """The 7B-scale kernel shape: qkv streamed in column phases (block 0
    STRADDLES the q|k boundary) + FFN zero-padded to 128-multiple blocks.
    Forced via an explicit decode_block_plan-style dict on a small config
    so the exact code path Llama-2-7B rides is parity-tested on chip."""
    from paddle_tpu.ops import fused_decode as fd
    from paddle_tpu.ops.rope import rope_cos_sin

    L, b, S, hd, h, ffn = 3, 4, 256, 64, 256, 384
    nh = nkv = 4                       # MHA, like llama2-7b
    dq, dkv = nh * hd, nkv * hd        # 256, 256; dqkv = 768
    blocks = {"q_split": 2, "qblk": 384, "ffn_blocks": 2, "fblk": 256,
              "ffn_pad": 512}
    r = np.random.RandomState(0)
    bf = lambda *s: jnp.asarray(r.randn(*s) * 0.05, jnp.bfloat16)
    params = {"ln1": jnp.ones((L, h), jnp.bfloat16),
              "ln2": jnp.ones((L, h), jnp.bfloat16)}
    shapes = {"wqkv": (L, h, dq + 2 * dkv), "wo": (L, dq, h),
              "wg": (L, h, ffn), "wu": (L, h, ffn), "wd": (L, ffn, h)}
    for k, s in shapes.items():
        if int8:
            params[k] = jnp.asarray(r.randint(-127, 128, s), jnp.int8)
            params[f"{k}_s"] = jnp.full((L, 1, s[-1]), 4e-4, jnp.float32)
        else:
            params[k] = bf(*s)
    params = fd._pad_ffn(params, blocks["ffn_pad"])
    x = bf(b, h)
    kv = bf(L, b, S, 2 * dkv)
    pos = 77
    cos, sin = rope_cos_sin(S, hd)

    xr, kvr = jax.jit(lambda *a: fd.fused_decode_reference(
        *a, num_heads=nh, num_kv_heads=nkv, eps=1e-5))(
        x, params, kv, pos, cos[pos:pos + 1], sin[pos:pos + 1])
    xp, kvp = jax.jit(lambda x, p, kv: fd._fused_decode_pallas(
        x, p, kv, pos, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        eps=1e-5, blocks=blocks))(x, params, kv)

    assert_close(xp, xr)
    d = np.abs(np.asarray(kvr, np.float32) - np.asarray(kvp, np.float32))
    touched = sorted(set(np.argwhere(d > 1e-3)[:, 2].tolist()))
    assert touched in ([], [pos]), touched
    assert d.max() < 0.05, d.max()


def test_stacked_decoder_generate_on_tpu():
    """StackedLlamaDecoder (the 7B serving engine) == layered generate,
    token for token, with the fused kernel engaged (strict mode)."""
    import paddle_tpu
    from paddle_tpu.inference import generate
    from paddle_tpu.inference.stacked import StackedLlamaDecoder
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle_tpu.seed(0)
    cfg = LlamaConfig(vocab_size=512, hidden_size=256, num_layers=3,
                      num_heads=4, num_kv_heads=2, intermediate_size=512,
                      max_position_embeddings=512)
    m = LlamaForCausalLM(cfg).bfloat16()
    state = m.state_dict(include_buffers=False)
    dec = StackedLlamaDecoder.from_state_dict(cfg, state)
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 9)))
    out_layered = generate(m, prompt, max_new_tokens=20, temperature=0.0)
    out_stacked = dec.generate(prompt, max_new_tokens=20, temperature=0.0)
    assert (np.asarray(out_layered).tolist()
            == np.asarray(out_stacked).tolist())


def test_fused_generate_matches_layered_on_tpu():
    import paddle_tpu
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.inference import generate
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle_tpu.seed(0)
    cfg = LlamaConfig(vocab_size=512, hidden_size=256, num_layers=3,
                      num_heads=4, num_kv_heads=2, intermediate_size=512,
                      max_position_embeddings=512)
    m = LlamaForCausalLM(cfg).bfloat16()
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 9)))
    out_fused = generate(m, prompt, max_new_tokens=20, temperature=0.0)
    m._generate_jit_cache = {}
    set_flags({"FLAGS_fused_decode": False})
    out_ref = generate(m, prompt, max_new_tokens=20, temperature=0.0)
    set_flags({"FLAGS_fused_decode": True})
    assert np.asarray(out_fused).tolist() == np.asarray(out_ref).tolist()


# ---------------------------------------------------------------------------
# rms_norm bench kernel
# ---------------------------------------------------------------------------

def test_rms_norm_pallas_parity():
    from paddle_tpu.ops import rms_norm as rn
    x = rand(12, 4, 512, 1024, dtype=jnp.bfloat16)
    w = rand(13, 1024, dtype=jnp.bfloat16, scale=1.0)
    pal = rn._rms_norm_pallas(x, w, 1e-5)
    ref = rn._rms_norm_ref(x, w, 1e-5)
    assert_close(pal, ref, rtol=1e-2, atol=1e-2)


def test_fused_decode_int8_generate_on_tpu():
    """Int8 weights inside the fused kernel (fused_multi_transformer_int8
    analog): greedy decode must track the unfused int8 scan decoder."""
    import paddle_tpu
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.inference import generate
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.quantization import quantize_model, quantized_state

    paddle_tpu.seed(0)
    cfg = LlamaConfig(vocab_size=512, hidden_size=256, num_layers=3,
                      num_heads=4, num_kv_heads=2, intermediate_size=512,
                      max_position_embeddings=512)
    m = LlamaForCausalLM(cfg).bfloat16()
    quantize_model(m)
    state = quantized_state(m)
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 9)))
    out_fused = generate(m, prompt, max_new_tokens=16, temperature=0.0,
                         state=state)
    m._generate_jit_cache = {}
    set_flags({"FLAGS_fused_decode": False, "FLAGS_pallas_strict": False})
    out_ref = generate(m, prompt, max_new_tokens=16, temperature=0.0,
                       state=state)
    set_flags({"FLAGS_fused_decode": True})
    match = (np.asarray(out_fused) == np.asarray(out_ref)).mean()
    assert match >= 0.9, match    # int8 near-ties may flip a token


def test_fused_decode_gpt_arch_on_tpu():
    """arch='gpt' kernel branch (LayerNorm+bias / MHA / no rope / GELU):
    greedy decode must match the layered scan decoder."""
    import paddle_tpu
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.inference import generate
    from paddle_tpu.models.gpt import GPTConfig, GPTPretrainModel

    paddle_tpu.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=3,
                    num_heads=2, max_position_embeddings=512,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    g = GPTPretrainModel(cfg).bfloat16()
    g.eval()
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 9)))
    out_fused = generate(g, prompt, max_new_tokens=16, temperature=0.0)
    g._generate_jit_cache = {}
    set_flags({"FLAGS_fused_decode": False, "FLAGS_pallas_strict": False})
    out_ref = generate(g, prompt, max_new_tokens=16, temperature=0.0)
    set_flags({"FLAGS_fused_decode": True})
    match = (np.asarray(out_fused) == np.asarray(out_ref)).mean()
    assert match >= 0.95, match


@pytest.mark.parametrize("b", [1, 2])
def test_fused_decode_moe_kernel_parity(b):
    """arch='moe' kernel: attention + in-kernel router + data-dependent
    expert-weight streaming vs the jnp reference twin."""
    from paddle_tpu.ops import fused_decode as fd
    from paddle_tpu.ops.rope import rope_cos_sin

    L, S, hd, h, ffn, E, k = 3, 256, 64, 256, 512, 8, 2
    nkv, rep = 2, 2
    nh = nkv * rep
    r = np.random.RandomState(0)
    f = lambda *s: jnp.asarray(r.randn(*s) * 0.05, jnp.bfloat16)
    params = {"ln1": jnp.ones((L, h), jnp.bfloat16),
              "wqkv": f(L, h, (nh + 2 * nkv) * hd),
              "wo": f(L, nh * hd, h), "ln2": jnp.ones((L, h), jnp.bfloat16),
              "gate": f(L, E, h),
              "weg": f(L, E, h, ffn), "weu": f(L, E, h, ffn),
              "wed": f(L, E, ffn, h)}
    x = f(b, h)
    kv = f(L, b, S, 2 * nkv * hd)
    pos = 130
    cos, sin = rope_cos_sin(S, hd)

    xr, kvr = jax.jit(lambda *a: fd.fused_decode_reference(
        *a, num_heads=nh, num_kv_heads=nkv, eps=1e-5, arch="moe",
        top_k=k))(x, params, kv, pos, cos[pos:pos + 1], sin[pos:pos + 1])
    xp, kvp = jax.jit(lambda x, p, kv: fd._fused_decode_moe_pallas(
        x, p, kv, pos, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        top_k=k, eps=1e-5))(x, params, kv)

    assert_close(xp, xr)
    d = np.abs(np.asarray(kvr, np.float32) - np.asarray(kvp, np.float32))
    touched = sorted(set(np.argwhere(d > 1e-3)[:, 2].tolist()))
    assert touched in ([], [pos]), touched
    assert d.max() < 0.05, d.max()


def test_fused_decode_moe_shared_experts_parity():
    """DeepSeekMoE shape: shared experts stream as Mosaic-pipelined dense
    SwiGLU blocks next to the routed top-k manual pipeline; k=4 multi-slot
    routing. Kernel vs the jnp reference twin."""
    from paddle_tpu.ops import fused_decode as fd
    from paddle_tpu.ops.rope import rope_cos_sin

    L, S, hd, h, ffn, E, k = 3, 256, 64, 256, 256, 16, 4
    fs = 2 * ffn                             # 2 shared experts
    nkv, rep, b = 2, 2, 2
    nh = nkv * rep
    r = np.random.RandomState(0)
    f = lambda *s: jnp.asarray(r.randn(*s) * 0.05, jnp.bfloat16)
    params = {"ln1": jnp.ones((L, h), jnp.bfloat16),
              "wqkv": f(L, h, (nh + 2 * nkv) * hd),
              "wo": f(L, nh * hd, h), "ln2": jnp.ones((L, h), jnp.bfloat16),
              "gate": f(L, E, h),
              "weg": f(L, E, h, ffn), "weu": f(L, E, h, ffn),
              "wed": f(L, E, ffn, h),
              "wsg": f(L, h, fs), "wsu": f(L, h, fs), "wsd": f(L, fs, h)}
    x = f(b, h)
    kv = f(L, b, S, 2 * nkv * hd)
    pos = 130
    cos, sin = rope_cos_sin(S, hd)

    xr, kvr = jax.jit(lambda *a: fd.fused_decode_reference(
        *a, num_heads=nh, num_kv_heads=nkv, eps=1e-5, arch="moe",
        top_k=k))(x, params, kv, pos, cos[pos:pos + 1], sin[pos:pos + 1])
    xp, kvp = jax.jit(lambda x, p, kv: fd._fused_decode_moe_pallas(
        x, p, kv, pos, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        top_k=k, eps=1e-5))(x, params, kv)

    assert_close(xp, xr)
    d = np.abs(np.asarray(kvr, np.float32) - np.asarray(kvp, np.float32))
    touched = sorted(set(np.argwhere(d > 1e-3)[:, 2].tolist()))
    assert touched in ([], [pos]), touched
    assert d.max() < 0.05, d.max()


def test_fused_decode_moe_generate_on_tpu():
    """End-to-end: Mixtral generate() rides the MoE kernel and matches the
    layered scan decoder greedily."""
    import paddle_tpu
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.inference import generate
    from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    paddle_tpu.seed(0)
    cfg = MixtralConfig(vocab_size=512, hidden_size=256, num_layers=3,
                        num_heads=4, num_kv_heads=2, intermediate_size=512,
                        max_position_embeddings=512, num_experts=8, top_k=2)
    m = MixtralForCausalLM(cfg).bfloat16()
    m.eval()
    # random-init expert probs are near-ties: one bf16-ulp difference
    # between the kernel and the scan path flips an expert and the greedy
    # sequences diverge (both valid). Scale the router weights so routing
    # is DECISIVE — then the two paths must agree token-for-token.
    for layer in m.model.layers:
        layer.moe.gate.proj.weight = layer.moe.gate.proj.weight * 8.0
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 9)))
    out_fused = generate(m, prompt, max_new_tokens=16, temperature=0.0)
    m._generate_jit_cache = {}
    set_flags({"FLAGS_fused_decode": False})
    out_ref = generate(m, prompt, max_new_tokens=16, temperature=0.0)
    set_flags({"FLAGS_fused_decode": True})
    assert np.asarray(out_fused).tolist() == np.asarray(out_ref).tolist()


def test_flash_padded_head_dim_and_kv_parity():
    """Padded dispatch (SD-1.5 shapes): head_dim 40 zero-padded to 64 and
    cross-attn KV 77 padded to 128 under kv_lens must match the XLA path."""
    from paddle_tpu.ops import flash_attention as fa

    r = np.random.RandomState(0)
    f = lambda *s: jnp.asarray(r.standard_normal(s) * 0.3, jnp.bfloat16)
    # self-attention, hd=40, s=1024
    q, k, v = f(2, 1024, 8, 40), f(2, 1024, 8, 40), f(2, 1024, 8, 40)
    out = fa.scaled_dot_product_attention(q, k, v)
    ref = fa._xla_attention(q, k, v)
    assert_close(out, ref)
    # cross-attention, hd=40, sk=77 (pads to 128 with kv_lens masking)
    kc, vc = f(2, 77, 8, 40), f(2, 77, 8, 40)
    out = fa.scaled_dot_product_attention(q, kc, vc)
    ref = fa._xla_attention(q, kc, vc)
    assert_close(out, ref)
    # grads for ALL operands flow through the pad/slice (dk/dv exercise
    # the bwd kernels on padded shapes; pad-region grads must vanish)
    def loss(q, kc, vc):
        return jnp.sum(fa.scaled_dot_product_attention(
            q, kc, vc).astype(jnp.float32) ** 2)
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, kc, vc)
    def loss_ref(q, kc, vc):
        return jnp.sum(fa._xla_attention(q, kc, vc).astype(jnp.float32) ** 2)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, kc, vc)
    for a, b in zip(g, g_ref):
        assert_close(a, b, rtol=5e-2, atol=5e-2)
    # segment ids with a padded KV: pad columns carry id -1 (matches no
    # query segment); a regression that pads with 0 would attend to
    # garbage KV rows
    seg_q = jnp.zeros((2, 1024), jnp.int32)
    seg_kc = jnp.zeros((2, 77), jnp.int32)
    out = fa.scaled_dot_product_attention(q, kc, vc, segment_ids=seg_q,
                                          kv_segment_ids=seg_kc)
    ref = fa._xla_attention(q, kc, vc, seg_q=seg_q, seg_k=seg_kc)
    assert_close(out, ref)


def test_flash_sliding_window_parity():
    """Causal sliding window (Mistral-style) in the kernels, fwd + all
    grads, vs the XLA dense-mask path."""
    b, s, h, d = 2, 1024, 4, 64
    q = rand(30, b, s, h, d)
    k = rand(31, b, s, h, d)
    v = rand(32, b, s, h, d)
    for w in (128, 200):     # block-aligned and unaligned windows
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             window_size=w)
        ref = fa._xla_attention(q, k, v, is_causal=True, window=w)
        assert_close(out, ref)

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2)

    gp = jax.jit(jax.grad(loss(lambda *a: F.scaled_dot_product_attention(
        *a, is_causal=True, window_size=200)), argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss(lambda *a: fa._xla_attention(
        *a, is_causal=True, window=200)), argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(gp, gr):
        assert_close(a, b_, rtol=5e-2, atol=5e-2)


def test_flash_alibi_parity():
    """ALiBi per-head linear bias inside the online softmax, fwd + grads,
    composed with the sliding window."""
    b, s, h, d = 2, 1024, 4, 64
    q = rand(33, b, s, h, d)
    k = rand(34, b, s, h, d)
    v = rand(35, b, s, h, d)
    slopes = jnp.asarray([2.0 ** (-i) for i in range(1, h + 1)],
                         jnp.float32)
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                         alibi_slopes=slopes)
    ref = fa._xla_attention(q, k, v, is_causal=True, alibi_slopes=slopes)
    assert_close(out, ref)
    # composed: window + alibi
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                         window_size=256,
                                         alibi_slopes=slopes)
    ref = fa._xla_attention(q, k, v, is_causal=True, window=256,
                            alibi_slopes=slopes)
    assert_close(out, ref)

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2)

    gp = jax.jit(jax.grad(loss(lambda *a: F.scaled_dot_product_attention(
        *a, is_causal=True, alibi_slopes=slopes)),
        argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss(lambda *a: fa._xla_attention(
        *a, is_causal=True, alibi_slopes=slopes)),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(gp, gr):
        assert_close(a, b_, rtol=5e-2, atol=5e-2)


# ---------------------------------------------------------------------------
# batched-head attention + int8 KV cache (decode-path overhaul PR)
# ---------------------------------------------------------------------------

def test_fused_decode_int8_cache_kernel_parity():
    """int8 KV cache mode on chip: the kernel (quantized RMW append +
    int8 chunk streaming + on-path dequant) vs the int8 reference twin —
    exact int8 cache agreement, close hidden state."""
    from paddle_tpu.ops import fused_decode as fd
    from paddle_tpu.ops.rope import rope_cos_sin

    L, b, S, hd, h, ffn = 3, 8, 256, 64, 256, 512
    nh = nkv = 4
    dq = dkv = nh * hd
    r = np.random.RandomState(0)
    f = lambda *s: jnp.asarray(r.randn(*s) * 0.05, jnp.bfloat16)
    params = {"ln1": jnp.ones((L, h), jnp.bfloat16),
              "wqkv": f(L, h, 3 * dq), "wo": f(L, dq, h),
              "ln2": jnp.ones((L, h), jnp.bfloat16),
              "wg": f(L, h, ffn), "wu": f(L, h, ffn), "wd": f(L, ffn, h)}
    x = f(b, h)
    # cache magnitudes must match the append distribution (post-RMS-norm
    # qkv products ~O(1)) so the calibrated scales cover the new token
    kvb = jnp.asarray(r.randn(L, b, S, 2 * dkv), jnp.bfloat16)
    kvi, scales = fd.quantize_kv_cache(kvb, nkv)
    pos = 130
    cos, sin = rope_cos_sin(S, hd)

    xr, kvr = jax.jit(lambda x, p, kv, s: fd.fused_decode_reference(
        x, p, kv, pos, cos[pos:pos + 1], sin[pos:pos + 1],
        num_heads=nh, num_kv_heads=nkv, eps=1e-5, kv_scales=s))(
        x, params, kvi, scales)
    xp, kvp = jax.jit(lambda x, p, kv, s: fd._fused_decode_pallas(
        x, p, kv, pos, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        eps=1e-5, kv_scales=s))(x, params, kvi, scales)

    assert_close(xp, xr)
    d = np.abs(np.asarray(kvr, np.int32) - np.asarray(kvp, np.int32))
    touched = sorted(set(np.argwhere(d > 1)[:, 2].tolist()))
    assert touched in ([], [pos]), touched   # off-append rows untouched
    assert d.max() <= 1, d.max()             # append rounding ulp at most


def test_fused_decode_int8_cache_long_context():
    """s >= 2048: the regime the int8 cache targets (cache bytes dominate
    the decode roofline). Kernel vs int8 reference at pos near the end of
    a 2048-slot cache."""
    from paddle_tpu.ops import fused_decode as fd
    from paddle_tpu.ops.rope import rope_cos_sin

    L, b, S, hd, h, ffn = 2, 4, 2048, 64, 256, 512
    nh = nkv = 4
    dq = dkv = nh * hd
    r = np.random.RandomState(1)
    f = lambda *s: jnp.asarray(r.randn(*s) * 0.05, jnp.bfloat16)
    params = {"ln1": jnp.ones((L, h), jnp.bfloat16),
              "wqkv": f(L, h, 3 * dq), "wo": f(L, dq, h),
              "ln2": jnp.ones((L, h), jnp.bfloat16),
              "wg": f(L, h, ffn), "wu": f(L, h, ffn), "wd": f(L, ffn, h)}
    x = f(b, h)
    kvb = jnp.asarray(r.randn(L, b, S, 2 * dkv), jnp.bfloat16)
    kvi, scales = fd.quantize_kv_cache(kvb, nkv)
    pos = 2005
    cos, sin = rope_cos_sin(S, hd)

    xr, _ = jax.jit(lambda x, p, kv, s: fd.fused_decode_reference(
        x, p, kv, pos, cos[pos:pos + 1], sin[pos:pos + 1],
        num_heads=nh, num_kv_heads=nkv, eps=1e-5, kv_scales=s))(
        x, params, kvi, scales)
    xp, _ = jax.jit(lambda x, p, kv, s: fd._fused_decode_pallas(
        x, p, kv, pos, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        eps=1e-5, kv_scales=s))(x, params, kvi, scales)
    assert_close(xp, xr)


@pytest.mark.parametrize("b", [1, 2])
def test_fused_decode_moe_int8_cache_kernel_parity(b):
    """MoE kernel int8 KV-cache mode on chip (b=1 exercises the
    prefetch-two-ahead expert pipeline at its worst slot count): k-scales
    folded into the block-diagonal q, v-scales on the attention output,
    quantized RMW append — vs the int8 reference twin."""
    from paddle_tpu.ops import fused_decode as fd
    from paddle_tpu.ops.rope import rope_cos_sin

    L, S, hd, h, ffn, E, k = 3, 256, 64, 256, 512, 8, 2
    nkv, rep = 2, 2
    nh = nkv * rep
    r = np.random.RandomState(0)
    f = lambda *s: jnp.asarray(r.randn(*s) * 0.05, jnp.bfloat16)
    params = {"ln1": jnp.ones((L, h), jnp.bfloat16),
              "wqkv": f(L, h, (nh + 2 * nkv) * hd),
              "wo": f(L, nh * hd, h), "ln2": jnp.ones((L, h), jnp.bfloat16),
              "gate": f(L, E, h),
              "weg": f(L, E, h, ffn), "weu": f(L, E, h, ffn),
              "wed": f(L, E, ffn, h)}
    x = f(b, h)
    kvb = jnp.asarray(r.randn(L, b, S, 2 * nkv * hd), jnp.bfloat16)
    kvi, scales = fd.quantize_kv_cache(kvb, nkv)
    pos = 130
    cos, sin = rope_cos_sin(S, hd)

    xr, kvr = jax.jit(lambda x, p, kv, s: fd.fused_decode_reference(
        x, p, kv, pos, cos[pos:pos + 1], sin[pos:pos + 1],
        num_heads=nh, num_kv_heads=nkv, eps=1e-5, arch="moe", top_k=k,
        kv_scales=s))(x, params, kvi, scales)
    xp, kvp = jax.jit(lambda x, p, kv, s: fd._fused_decode_moe_pallas(
        x, p, kv, pos, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        top_k=k, eps=1e-5, kv_scales=s,
        blocks={"cache_wbytes": 1}))(x, params, kvi, scales)

    assert_close(xp, xr)
    d = np.abs(np.asarray(kvr, np.int32) - np.asarray(kvp, np.int32))
    touched = sorted(set(np.argwhere(d > 1)[:, 2].tolist()))
    assert touched in ([], [pos]), touched
    assert d.max() <= 1, d.max()


def test_fused_decode_moe_int8_generate_on_tpu():
    """End-to-end Mixtral generate(cache_dtype=int8) on the MoE kernel
    tracks the bf16-cache kernel run (prefill-calibrated scales)."""
    import paddle_tpu
    from paddle_tpu.inference import generate
    from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    paddle_tpu.seed(0)
    cfg = MixtralConfig(vocab_size=512, hidden_size=256, num_layers=3,
                        num_heads=4, num_kv_heads=2, intermediate_size=512,
                        max_position_embeddings=512, num_experts=8, top_k=2)
    m = MixtralForCausalLM(cfg).bfloat16()
    m.eval()
    for layer in m.model.layers:     # decisive routing (see moe generate
        layer.moe.gate.proj.weight = layer.moe.gate.proj.weight * 8.0
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 9)))
    out16 = generate(m, prompt, max_new_tokens=16, temperature=0.0)
    m._generate_jit_cache = {}
    out8 = generate(m, prompt, max_new_tokens=16, temperature=0.0,
                    cache_dtype=jnp.int8)
    match = (np.asarray(out16) == np.asarray(out8)).mean()
    assert match >= 0.9, match   # int8-cache near-ties may flip a token


def test_fused_decode_moe_prefetch_many_slots_on_tpu():
    """k=4 routing at b=2 (8 expert-FFN steps): the triple-buffered
    prefetch pipeline reuses every VMEM buffer — strict on-chip parity."""
    from paddle_tpu.ops import fused_decode as fd
    from paddle_tpu.ops.rope import rope_cos_sin

    L, S, hd, h, ffn, E, k, b = 2, 256, 64, 256, 256, 16, 4, 2
    nkv, rep = 2, 2
    nh = nkv * rep
    r = np.random.RandomState(0)
    f = lambda *s: jnp.asarray(r.randn(*s) * 0.05, jnp.bfloat16)
    params = {"ln1": jnp.ones((L, h), jnp.bfloat16),
              "wqkv": f(L, h, (nh + 2 * nkv) * hd),
              "wo": f(L, nh * hd, h), "ln2": jnp.ones((L, h), jnp.bfloat16),
              "gate": f(L, E, h),
              "weg": f(L, E, h, ffn), "weu": f(L, E, h, ffn),
              "wed": f(L, E, ffn, h)}
    x = f(b, h)
    kv = f(L, b, S, 2 * nkv * hd)
    pos = 77
    cos, sin = rope_cos_sin(S, hd)
    xr, _ = jax.jit(lambda *a: fd.fused_decode_reference(
        *a, num_heads=nh, num_kv_heads=nkv, eps=1e-5, arch="moe",
        top_k=k))(x, params, kv, pos, cos[pos:pos + 1], sin[pos:pos + 1])
    xp, _ = jax.jit(lambda x, p, kv: fd._fused_decode_moe_pallas(
        x, p, kv, pos, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        top_k=k, eps=1e-5))(x, params, kv)
    assert_close(xp, xr)


def test_stacked_decoder_int8_cache_generate_on_tpu():
    """StackedLlamaDecoder int8-cache greedy decode tracks the bf16-cache
    run (prefill-calibrated scales)."""
    import paddle_tpu
    from paddle_tpu.inference.stacked import StackedLlamaDecoder
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle_tpu.seed(0)
    cfg = LlamaConfig(vocab_size=512, hidden_size=256, num_layers=3,
                      num_heads=4, num_kv_heads=2, intermediate_size=512,
                      max_position_embeddings=512)
    m = LlamaForCausalLM(cfg).bfloat16()
    dec = StackedLlamaDecoder.from_state_dict(
        cfg, m.state_dict(include_buffers=False))
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 9)))
    out16 = dec.generate(prompt, max_new_tokens=20, temperature=0.0)
    out8 = dec.generate(prompt, max_new_tokens=20, temperature=0.0,
                        cache_dtype=jnp.int8)
    match = (np.asarray(out16) == np.asarray(out8)).mean()
    assert match >= 0.9, match   # int8-cache near-ties may flip a token


# ---------------------------------------------------------------------------
# continuous-batching serving engine (paged KV pool on the fused kernel)
# ---------------------------------------------------------------------------

def _serving_llama(L=3):
    import paddle_tpu
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig(vocab_size=512, hidden_size=128, num_layers=L,
                      num_heads=4, num_kv_heads=4, intermediate_size=256,
                      max_position_embeddings=512)
    paddle_tpu.seed(0)
    m = LlamaForCausalLM(cfg).bfloat16()
    m.eval()
    return m


@pytest.mark.parametrize("cache_dtype", [jnp.bfloat16, jnp.int8])
def test_serving_paged_kernel_token_exact_on_tpu(cache_dtype):
    """On-chip twin of tests/test_serving.py TestInterpretKernelParity:
    the real paged Pallas kernel (block-table DMA walk, strict mode)
    under the continuous-batching engine — merged-batch tokens must be
    identical to isolated contiguous-kernel generate, bf16 and int8
    pools."""
    from paddle_tpu import serving
    from paddle_tpu.inference import generate

    m = _serving_llama()
    rng = np.random.RandomState(11)
    prompts = [rng.randint(3, 512, (n,)) for n in (7, 21, 33)]
    max_new = [6, 6, 9]
    iso = [np.asarray(generate(m, p[None], max_new_tokens=mn,
                               temperature=0.0, cache_dtype=cache_dtype))
           [0, len(p):] for p, mn in zip(prompts, max_new)]
    eng = serving.ServingEngine(m, max_slots=2, block_tokens=16,
                                max_seq_len=64, cache_dtype=cache_dtype)
    rids = [eng.submit(serving.Request(p, max_new_tokens=mn))
            for p, mn in zip(prompts, max_new)]
    eng.drain(max_steps=100)
    for rid, ref in zip(rids, iso):
        assert eng.results[rid].tokens.tolist() == ref.tolist()


def test_serving_prefix_reuse_on_tpu():
    """Prefix-cache hit on the real chip: the second request adopts the
    cached blocks (no re-prefill of the shared prefix) and still matches
    isolated generate token-exact; shared block payloads stay untouched
    (copy-on-write)."""
    from paddle_tpu import serving
    from paddle_tpu.inference import generate

    m = _serving_llama()
    rng = np.random.RandomState(5)
    sys_p = rng.randint(3, 512, (40,))
    pr_a = np.concatenate([sys_p, rng.randint(3, 512, (5,))])
    pr_b = np.concatenate([sys_p, rng.randint(3, 512, (9,))])
    iso = [np.asarray(generate(m, p[None], max_new_tokens=8,
                               temperature=0.0))[0, len(p):]
           for p in (pr_a, pr_b)]
    eng = serving.ServingEngine(m, max_slots=2, block_tokens=16,
                                max_seq_len=128)
    ra = eng.submit(serving.Request(pr_a, max_new_tokens=8))
    eng.drain()
    shared = [e.block_id for e in
              eng.prefix_cache.lookup(pr_b, len(pr_b) // 16)]
    assert len(shared) == 2
    before = np.asarray(eng.kv_pool[:, shared].astype(jnp.float32))
    rb = eng.submit(serving.Request(pr_b, max_new_tokens=8))
    eng.drain()
    after = np.asarray(eng.kv_pool[:, shared].astype(jnp.float32))
    np.testing.assert_array_equal(before, after)
    assert eng.results[ra].tokens.tolist() == iso[0].tolist()
    assert eng.results[rb].tokens.tolist() == iso[1].tolist()
    assert eng.results[rb].prefix_hit_blocks == 2


def test_serving_gpt_paged_on_tpu():
    """GPT arch through the paged kernel on-chip (pre-LN + learned
    position embeddings take the gpt branch of the chunk walk)."""
    import paddle_tpu
    from paddle_tpu import serving
    from paddle_tpu.inference import generate
    from paddle_tpu.models.gpt import GPTConfig, GPTPretrainModel

    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=2, max_position_embeddings=256,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle_tpu.seed(0)
    g = GPTPretrainModel(cfg)
    g.eval()
    rng = np.random.RandomState(12)
    prompts = [rng.randint(3, 256, (n,)) for n in (6, 13)]
    iso = [np.asarray(generate(g, p[None], max_new_tokens=5,
                               temperature=0.0))[0, len(p):]
           for p in prompts]
    eng = serving.ServingEngine(g, max_slots=2, block_tokens=16,
                                max_seq_len=64)
    rids = [eng.submit(serving.Request(p, max_new_tokens=5))
            for p in prompts]
    eng.drain(max_steps=50)
    for rid, ref in zip(rids, iso):
        assert eng.results[rid].tokens.tolist() == ref.tolist()


@pytest.mark.parametrize("cache_dtype", [jnp.bfloat16, jnp.int8])
def test_serving_chunked_prefill_on_tpu(cache_dtype):
    """On-chip twin of tests/test_serving_chunked.py: chunked prefill
    (chunk programs appending block-aligned KV into the pool the real
    paged kernel then walks) must be token-identical to isolated
    generate — bf16 appends per chunk, int8 defers calibration+
    quantization to the last chunk. On TPU the chunk programs alias
    the donated pool (no CPU copy-per-chunk caveat — BENCH_r06)."""
    from paddle_tpu import serving
    from paddle_tpu.inference import generate

    m = _serving_llama()
    rng = np.random.RandomState(13)
    prompts = [rng.randint(3, 512, (n,)) for n in (40, 21, 9)]
    max_new = [6, 6, 8]
    iso = [np.asarray(generate(m, p[None], max_new_tokens=mn,
                               temperature=0.0, cache_dtype=cache_dtype))
           [0, len(p):] for p, mn in zip(prompts, max_new)]
    eng = serving.ServingEngine(m, max_slots=2, block_tokens=16,
                                max_seq_len=64, cache_dtype=cache_dtype,
                                chunk_tokens=16)
    rids = [eng.submit(serving.Request(p, max_new_tokens=mn))
            for p, mn in zip(prompts, max_new)]
    eng.drain(max_steps=200)
    for rid, ref in zip(rids, iso):
        assert eng.results[rid].tokens.tolist() == ref.tolist()
    assert eng.stats["prefill_chunks"] >= 3 + 2 + 1
    eng.close()


@pytest.mark.parametrize("cache_dtype", [jnp.bfloat16, jnp.int8])
def test_serving_speculative_on_tpu(cache_dtype):
    """On-chip twin of tests/test_serving_spec.py: the real paged
    VERIFY kernel (chunk walk + k-token causal tail, multi-token
    segment RMW appends, strict mode) under the speculative engine —
    committed tokens must be identical to isolated generate, bf16 and
    int8 pools, and the repetitive prompt must actually speculate
    (accepted > 0, tokens > dispatches)."""
    from paddle_tpu import serving
    from paddle_tpu.inference import generate

    m = _serving_llama()
    rng = np.random.RandomState(14)
    motif = rng.randint(3, 512, (8,))
    prompts = [np.tile(motif, 4), rng.randint(3, 512, (21,))]
    max_new = [16, 8]
    iso = [np.asarray(generate(m, p[None], max_new_tokens=mn,
                               temperature=0.0, cache_dtype=cache_dtype))
           [0, len(p):] for p, mn in zip(prompts, max_new)]
    eng = serving.ServingEngine(m, max_slots=2, block_tokens=16,
                                max_seq_len=64, cache_dtype=cache_dtype,
                                speculate=serving.SpecConfig(k=3))
    rids = [eng.submit(serving.Request(p, max_new_tokens=mn))
            for p, mn in zip(prompts, max_new)]
    eng.drain(max_steps=100)
    for rid, ref in zip(rids, iso):
        assert eng.results[rid].tokens.tolist() == ref.tolist()
    assert eng.stats["spec_accepted"] > 0
    assert eng.stats["decode_tokens"] > eng.stats["steps"]
    eng.close()


def test_serving_speculative_draft_on_tpu():
    """Draft-model proposer on-chip: the draft rides its own paged
    pool through the real kernels (round = scanned paged decode steps,
    prefill scatter), target verify through the verify kernel —
    token-exact vs isolated generate with near-total acceptance for a
    same-weights draft."""
    from paddle_tpu import serving
    from paddle_tpu.inference import generate

    m = _serving_llama()
    draft = _serving_llama()
    rng = np.random.RandomState(15)
    prompts = [rng.randint(3, 512, (n,)) for n in (9, 21)]
    iso = [np.asarray(generate(m, p[None], max_new_tokens=10,
                               temperature=0.0))[0, len(p):]
           for p in prompts]
    eng = serving.ServingEngine(
        m, max_slots=2, block_tokens=16, max_seq_len=64,
        speculate=serving.SpecConfig(k=3, proposer="draft",
                                     draft_model=draft))
    rids = [eng.submit(serving.Request(p, max_new_tokens=10))
            for p in prompts]
    eng.drain(max_steps=100)
    for rid, ref in zip(rids, iso):
        assert eng.results[rid].tokens.tolist() == ref.tolist()
    assert eng.stats["spec_accepted"] > 0
    eng.close()
