// Native data-pipeline kernels for paddle_tpu.
//
// Reference (SURVEY.md §2.7-data): the reference backs paddle.io.DataLoader
// with C++ reader ops and shared-memory worker queues
// (paddle/fluid/operators/reader/, python/paddle/io/). On TPU the device
// side is jax; the host-side hot loops — deterministic epoch shuffling and
// packing tokenized documents into fixed-length training rows — are the
// native surface, implemented here and exposed through ctypes
// (paddle_tpu/io/native.py), with NumPy fallbacks when no toolchain exists.
//
// Build: g++ -O3 -shared -fPIC -o libpaddle_tpu_data.so data_pipeline.cc

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// splitmix64 — deterministic, seed-stable across platforms
static inline uint64_t next_rand(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// Fisher-Yates over an index array (epoch shuffle).
void shuffle_indices(int64_t* idx, int64_t n, uint64_t seed) {
  uint64_t s = seed ^ 0xda3e39cb94b95bdbULL;
  for (int64_t i = n - 1; i > 0; --i) {
    uint64_t j = next_rand(&s) % static_cast<uint64_t>(i + 1);
    int64_t t = idx[i];
    idx[i] = idx[j];
    idx[j] = t;
  }
}

// Pack documents (concatenated token stream + offsets) into fixed-length
// rows, separated by eos_id, documents taken in doc_order. Rows are filled
// greedily and split across row boundaries (standard LM pretrain packing).
// Returns the number of rows fully written.
int64_t pack_documents(const int32_t* tokens, const int64_t* doc_offsets,
                       int64_t n_docs, const int64_t* doc_order,
                       int32_t* out, int64_t rows, int64_t row_len,
                       int32_t eos_id) {
  int64_t r = 0, c = 0;
  for (int64_t d = 0; d < n_docs && r < rows; ++d) {
    int64_t doc = doc_order ? doc_order[d] : d;
    int64_t beg = doc_offsets[doc], end = doc_offsets[doc + 1];
    for (int64_t t = beg; t < end && r < rows; ++t) {
      out[r * row_len + c] = tokens[t];
      if (++c == row_len) { c = 0; ++r; }
    }
    if (r >= rows) break;
    out[r * row_len + c] = eos_id;
    if (++c == row_len) { c = 0; ++r; }
  }
  // pad the trailing partial row with eos
  if (r < rows && c > 0) {
    for (; c < row_len; ++c) out[r * row_len + c] = eos_id;
    ++r;
  }
  return r;
}

// Gather rows from a flat token buffer: out[i] = tokens[idx[i]*row_len ..]
// (shuffled batch assembly without Python-loop copies).
void gather_rows(const int32_t* tokens, const int64_t* idx, int64_t n_rows,
                 int64_t row_len, int32_t* out) {
  for (int64_t i = 0; i < n_rows; ++i) {
    std::memcpy(out + i * row_len, tokens + idx[i] * row_len,
                sizeof(int32_t) * static_cast<size_t>(row_len));
  }
}

}  // extern "C"
