"""paddle.distribution parity — core distributions over jax.random.

Reference: python/paddle/distribution/ (Distribution base, Normal,
Uniform, Categorical, Bernoulli, kl_divergence). Sampling draws keys from
the framework RNG (`paddle_tpu.core.rng`), so `paddle.seed` governs it.
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import rng as _rng


class Distribution:
    # subclasses with a pathwise (reparameterized) sampler set this
    _has_rsample = False

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        """Reparameterized sample — only for distributions with a pathwise
        gradient (Normal, Uniform). Discrete distributions raise instead
        of silently returning zero gradients."""
        if not self._has_rsample:
            raise NotImplementedError(
                f"{type(self).__name__} has no reparameterized sampler; "
                "use sample() + a score-function estimator (log_prob)")
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    _has_rsample = True

    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return jnp.square(self.scale)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(_rng.next_rng_key("distribution"), shape)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        var = jnp.square(self.scale)
        return (-jnp.square(value - self.loc) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale) \
            + jnp.zeros_like(self.loc)

    def kl_divergence(self, other):
        var_ratio = jnp.square(self.scale / other.scale)
        t1 = jnp.square((self.loc - other.loc) / other.scale)
        return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))


class Uniform(Distribution):
    _has_rsample = True

    def __init__(self, low, high, name=None):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(_rng.next_rng_key("distribution"), shape)
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        inside = (value >= self.low) & (value <= self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = jnp.asarray(probs, jnp.float32)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = jnp.asarray(logits, jnp.float32)
            self.probs = jax.nn.sigmoid(self.logits)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        shape = tuple(shape) + self.probs.shape
        return jax.random.bernoulli(_rng.next_rng_key("distribution"),
                                    self.probs, shape).astype(jnp.float32)

    def log_prob(self, value):
        value = jnp.asarray(value, jnp.float32)
        return (value * jax.nn.log_sigmoid(self.logits)
                + (1.0 - value) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs
        return -(p * jnp.log(jnp.maximum(p, 1e-12))
                 + (1 - p) * jnp.log(jnp.maximum(1 - p, 1e-12)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if logits is not None:
            self.logits = jnp.asarray(logits, jnp.float32)
        else:
            self.logits = jnp.log(jnp.asarray(probs, jnp.float32))
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)

    @property
    def probs(self):
        return jnp.exp(self._log_p)

    def sample(self, shape=()):
        return jax.random.categorical(
            _rng.next_rng_key("distribution"), self.logits,
            shape=tuple(shape) + self.logits.shape[:-1])

    def log_prob(self, value):
        value = jnp.asarray(value, jnp.int32)
        batch = jnp.broadcast_shapes(value.shape, self._log_p.shape[:-1])
        logp = jnp.broadcast_to(self._log_p, batch + self._log_p.shape[-1:])
        value = jnp.broadcast_to(value, batch)
        return jnp.take_along_axis(logp, value[..., None], axis=-1)[..., 0]

    def entropy(self):
        return -jnp.sum(jnp.exp(self._log_p) * self._log_p, axis=-1)

    def kl_divergence(self, other):
        return jnp.sum(jnp.exp(self._log_p) * (self._log_p - other._log_p),
                       axis=-1)


def kl_divergence(p: Distribution, q: Distribution):
    """Dispatch kl (reference paddle.distribution.kl_divergence)."""
    if hasattr(p, "kl_divergence") and type(p) is type(q):
        return p.kl_divergence(q)
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")
