"""Name-based call-graph approximation over the package.

The ``traced-branch`` and jit-scoped ``host-sync`` checks need to know
which functions can execute *under a trace* — a ``float(x)`` in a
helper is harmless Python until some ``@jax.jit`` entry point calls it
with a tracer. Whole-program points-to analysis is out of scope for a
linter; this module builds the standard cheap approximation:

* **entries** — functions wrapped by ``jax.jit`` / ``pjit`` /
  ``shard_map`` (decorator form, ``jax.jit(f)`` call form, and lambdas
  passed to them), plus anything passed to ``lax`` control-flow
  combinators (``lax.scan``/``cond``/``while_loop``/``fori_loop`` run
  their operands traced);
* **edges** — resolved by NAME, within the defining module first, then
  through that module's explicit imports (``from paddle_tpu.x import
  f`` / ``import paddle_tpu.x as m; m.f(...)``). ``self.f(...)`` and
  ``cls.f(...)`` resolve to any same-module method called ``f``.

Tracing wrappers are matched by their 0.9 public names AND the
``core/jaxcompat.py`` shim spellings: a from-import alias of a wrapper
(``from jax.experimental.shard_map import shard_map as _esm`` — the
0.4.x graft underneath ``jax.shard_map``) marks entries exactly like
the canonical name, and function operands wrapped in
``functools.partial(f, ...)`` are peeled (``shard_map(partial(local,
axis_name=ax), ...)`` marks ``local``). Without this, call sites that
spell the wrapper through the compat layer would silently fall out of
the traced set on 0.4.x — the ``collective-axis``/``traced-branch``
rules must resolve the same sites on both jax versions.

False edges (two modules defining the same helper name) only ever make
the dependent rules MORE conservative — a function is flagged as
jit-reachable when it is not — and the baseline + inline suppressions
absorb that. Missed edges (getattr dispatch, callables threaded
through dicts like the fused-decode plans) are the approximation's
documented blind spot; the runtime sanitizer (analysis/runtime.py) is
the enforcement layer that does not depend on static reachability.
"""

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["CallGraph", "build_callgraph"]

#: callables whose function-valued arguments execute traced
_TRACING_WRAPPERS = {
    "jit", "pjit", "shard_map", "scan", "cond", "while_loop",
    "fori_loop", "switch", "associative_scan", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "vmap", "pmap", "grad", "value_and_grad",
}

#: module aliases that are never package-internal call targets
_EXTERNAL_ROOTS = {
    "np", "numpy", "jnp", "jax", "lax", "os", "sys", "math", "time",
    "json", "logging", "re", "ast", "threading", "functools",
    "itertools", "collections", "heapq", "bisect",
}


class _FuncInfo:
    __slots__ = ("module", "qualname", "name", "node", "calls", "entry")

    def __init__(self, module: str, qualname: str, node):
        self.module = module
        self.qualname = qualname
        self.name = qualname.rsplit(".", 1)[-1]
        self.node = node
        # (kind, name) call targets: kind 'local' (bare / self.) or
        # ('module', alias) for alias.attr(...) calls
        self.calls: List[Tuple[str, str]] = []
        self.entry = False


class CallGraph:
    """Jit-reachability oracle: ``is_traced(module, qualname)``."""

    def __init__(self):
        self.funcs: Dict[Tuple[str, str], _FuncInfo] = {}
        self._by_module_name: Dict[Tuple[str, str], List[_FuncInfo]] = {}
        # per module: local name -> (source module, original name) for
        # from-imports (the original name, so `from x import f as g`
        # resolves g back to x.f), and alias -> module path for module
        # imports — `import paddle_tpu.x as m` AND the module form of a
        # from-import, `from paddle_tpu import helpers as h` (both make
        # `alias.f(...)` calls resolvable)
        self.from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self.module_imports: Dict[str, Dict[str, str]] = {}
        self._traced: Set[Tuple[str, str]] = set()

    def add(self, info: _FuncInfo):
        self.funcs[(info.module, info.qualname)] = info
        self._by_module_name.setdefault(
            (info.module, info.name), []).append(info)

    def _resolve(self, module: str, name: str) -> List[_FuncInfo]:
        hits = self._by_module_name.get((module, name))
        if hits:
            return hits
        src = self.from_imports.get(module, {}).get(name)
        if src is not None:
            src_module, orig = src
            return self._by_module_name.get((src_module, orig), [])
        return []

    def finalize(self):
        """BFS the traced set from the entry functions."""
        work = [f for f in self.funcs.values() if f.entry]
        self._traced = {(f.module, f.qualname) for f in work}
        while work:
            f = work.pop()
            for kind, name in f.calls:
                if kind == "local":
                    targets = self._resolve(f.module, name)
                else:
                    mod = self.module_imports.get(f.module, {}).get(kind)
                    targets = (self._by_module_name.get((mod, name), [])
                               if mod is not None else [])
                for t in targets:
                    key = (t.module, t.qualname)
                    if key not in self._traced:
                        self._traced.add(key)
                        work.append(t)

    def is_traced(self, module: str, qualname: str) -> bool:
        return (module, qualname) in self._traced

    def traced_functions(self) -> Set[Tuple[str, str]]:
        return set(self._traced)


def _call_root(node) -> Optional[Tuple[str, str]]:
    """('local', name) for f(...) / self.f(...), (alias, attr) for
    alias.f(...); None for anything deeper (a.b.c(...))."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return ("local", fn.id)
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        base = fn.value.id
        if base in ("self", "cls"):
            return ("local", fn.attr)
        return (base, fn.attr)
    return None


def _is_tracing_wrapper(fn, aliases: frozenset = frozenset()) -> bool:
    """Does this callee trace its function arguments (jax.jit, pjit,
    lax.scan, functools.partial(jax.jit, ...))? ``aliases`` carries the
    module's from-import aliases of wrapper names (the jaxcompat shim
    spelling ``from jax.experimental.shard_map import shard_map as
    _esm``)."""
    if isinstance(fn, ast.Name):
        return fn.id in _TRACING_WRAPPERS or fn.id in aliases
    if isinstance(fn, ast.Attribute):
        return fn.attr in _TRACING_WRAPPERS or fn.attr in aliases
    if isinstance(fn, ast.Call):        # partial(jax.jit, ...)
        return any(_is_tracing_wrapper(a, aliases) for a in fn.args) \
            or _is_tracing_wrapper(fn.func, aliases)
    return False


def _is_partial(fn) -> bool:
    """functools.partial / partial — the wrapper the pipeline and
    context-parallel code curry shard_map bodies through."""
    if isinstance(fn, ast.Name):
        return fn.id == "partial"
    return isinstance(fn, ast.Attribute) and fn.attr == "partial"


class _ModuleVisitor(ast.NodeVisitor):
    def __init__(self, graph: CallGraph, module: str,
                 pending_entries: List[Tuple[str, str]]):
        self.graph = graph
        self.module = module
        self.stack: List[str] = []          # qualname parts
        self.func_stack: List[_FuncInfo] = []
        # (module, name) entry marks, resolved AFTER every module's
        # defs exist — a jax.jit(f) in module A may name a function
        # module A imports from module B
        self._pending = pending_entries
        # local from-import aliases of tracing wrappers (the jaxcompat
        # shim spelling): `from jax.experimental.shard_map import
        # shard_map as _esm` makes _esm(f, ...) an entry mark
        self.wrapper_aliases: set = set()
        graph.from_imports.setdefault(module, {})
        graph.module_imports.setdefault(module, {})

    # -------------------------------------------------------- imports
    def visit_ImportFrom(self, node):
        if node.module and node.level == 0:
            for a in node.names:
                if a.name in _TRACING_WRAPPERS and a.asname:
                    self.wrapper_aliases.add(a.asname)
                local = a.asname or a.name
                self.graph.from_imports[self.module][local] = (
                    node.module, a.name)
                # the imported name may itself be a MODULE (`from
                # paddle_tpu.ops import rope as rope_ops`): also record
                # the candidate submodule path so `local.f(...)` calls
                # resolve — a wrong guess just resolves to no defs
                self.graph.module_imports[self.module][local] = (
                    f"{node.module}.{a.name}")
        self.generic_visit(node)

    def visit_Import(self, node):
        for a in node.names:
            alias = a.asname or a.name.split(".")[0]
            if alias not in _EXTERNAL_ROOTS:
                self.graph.module_imports[self.module][alias] = a.name
        self.generic_visit(node)

    # ------------------------------------------------------------ defs
    def _visit_func(self, node):
        qual = ".".join(self.stack + [node.name])
        info = _FuncInfo(self.module, qual, node)
        aliases = frozenset(self.wrapper_aliases)
        for dec in node.decorator_list:
            if _is_tracing_wrapper(dec, aliases) or (
                    isinstance(dec, ast.Call)
                    and _is_tracing_wrapper(dec.func, aliases)):
                info.entry = True
        self.graph.add(info)
        self.stack.append(node.name)
        self.func_stack.append(info)
        self.generic_visit(node)
        self.func_stack.pop()
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_Lambda(self, node):
        # lambdas passed to jit are handled at the Call site (their
        # body's calls attribute to the enclosing function, which is
        # correct: if the enclosing function builds a jitted lambda,
        # the names the lambda calls run traced)
        self.generic_visit(node)

    # ----------------------------------------------------------- calls
    def visit_Call(self, node):
        if self.func_stack:
            root = _call_root(node)
            if root is not None:
                kind, name = root
                if kind == "local" or kind not in _EXTERNAL_ROOTS:
                    self.func_stack[-1].calls.append((kind, name))
        if _is_tracing_wrapper(node.func, frozenset(self.wrapper_aliases)):
            # jax.jit(f) / lax.scan(step, ...): every function-valued
            # argument becomes a trace entry
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, ast.Call) and _is_partial(a.func) \
                        and a.args:
                    # peel partial(f, ...): the curried callable is the
                    # traced operand (shard_map(partial(local, ...)))
                    a = a.args[0]
                if isinstance(a, ast.Name):
                    self._mark_entry(a.id)
                elif isinstance(a, ast.Attribute) \
                        and isinstance(a.value, ast.Name) \
                        and a.value.id in ("self", "cls"):
                    self._mark_entry(a.attr)
                elif isinstance(a, ast.Lambda) and self.func_stack:
                    # treat the enclosing function's recorded calls as
                    # potentially-traced: mark targets the lambda body
                    # names directly
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Call):
                            r = _call_root(sub)
                            if r is not None and r[0] == "local":
                                self._mark_entry(r[1])
        self.generic_visit(node)

    def _mark_entry(self, name: str):
        self._pending.append((self.module, name))


def build_callgraph(files: Dict[str, ast.Module]) -> CallGraph:
    """``files`` maps repo-relative module paths to parsed ASTs."""
    graph = CallGraph()
    pending: List[Tuple[str, str]] = []
    for path, tree in files.items():
        module = os.path.splitext(path)[0].replace(os.sep, ".")
        if module.endswith(".__init__"):
            module = module[: -len(".__init__")]
        _ModuleVisitor(graph, module, pending).visit(tree)
    # entries recorded by (module, name) resolve only after every
    # module's defs exist
    for module, name in pending:
        for t in graph._resolve(module, name):
            t.entry = True
    graph.finalize()
    return graph
