"""tpu-lint driver: file walking, suppressions, baseline filtering.

The performance claims this repo makes — "no steady-state H2D",
"byte-identical hot path when disarmed", "untraced path bit-identical"
— are invariants about *where the code syncs, recompiles and branches
on traced values*. This pass makes them structural: analysis/rules.py
holds the checks, this module turns them into a repeatable gate:

* ``run_lint(root)`` — all findings for the package;
* inline ``# tpu-lint: allow(<rule>[, <rule>...]): reason`` on the
  flagged line (or the line directly above it) suppresses an
  *intentional* site — the reason is the point: every suppression is a
  classified sync;
* ``# tpu-lint: allow-file(<rule>): reason`` in a module's first 30
  lines suppresses a rule for a whole eager-only module (the
  data-dependent-shape helpers in tensor/extra_ops.py, vision/ops.py);
* the checked-in ``analysis/baseline.json`` pins violations that
  predate the linter, so ``--check`` fails only on NEW ones
  (analysis/baseline.py; ``--update-baseline`` regenerates it).

The lint path never imports jax — ``python -m paddle_tpu.analysis``
must stay fast enough (<20 s, pinned by tests/test_analysis.py) to run
as a tier-1 test and as the gate the future to_static/compile-cache
layer is validated against.
"""

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from paddle_tpu.analysis import baseline as baseline_mod
from paddle_tpu.analysis import callgraph as callgraph_mod
from paddle_tpu.analysis import rules as rules_mod
from paddle_tpu.analysis.rules import ALL_RULES, Finding, SourceFile

__all__ = ["ALL_RULES", "Finding", "LintResult", "repo_root",
           "package_sources", "run_lint"]

_ALLOW_LINE = re.compile(
    r"#\s*tpu-lint:\s*allow\(([a-z0-9_,\- ]+)\)")
_ALLOW_FILE = re.compile(
    r"#\s*tpu-lint:\s*allow-file\(([a-z0-9_,\- ]+)\)")
# `# tpu-lint: volatile(reason)` — the snapshot-coverage rule's
# field-level classification: "this mutable field is rebuilt, not
# serialized, and here is why". Sugar for allow(snapshot-coverage)
# with the reason inside the parens (docs/ANALYSIS.md).
_VOLATILE_LINE = re.compile(r"#\s*tpu-lint:\s*volatile\(")
_ALLOW_FILE_SCAN_LINES = 30


def repo_root() -> str:
    """The directory holding the ``paddle_tpu`` package (and docs/)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _iter_py_files(pkg_dir: str):
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__")
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def package_sources(root: Optional[str] = None) -> Dict[str, SourceFile]:
    """Repo-relative path -> SourceFile for every module in
    ``paddle_tpu/`` (deterministic order: sorted walk)."""
    root = root or repo_root()
    pkg = os.path.join(root, "paddle_tpu")
    files: Dict[str, SourceFile] = {}
    for abspath in _iter_py_files(pkg):
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:    # pragma: no cover - package parses
            raise SyntaxError(f"tpu-lint cannot parse {rel}: {e}") from e
        files[rel] = SourceFile(rel, src, tree)
    return files


def _suppressions(sf: SourceFile) -> Tuple[Dict[int, set], set]:
    """(line -> allowed rules, file-level allowed rules).

    An inline pragma (code + comment on one line) covers its own line.
    A comment-ONLY pragma line covers the next statement — its full
    multi-line span for a simple statement (an annotation above a
    wrapped expression reaches a finding on any continuation line),
    but only the HEADER of a compound statement (if/for/with/def):
    covering the whole block would let a future violation inside it
    ride an annotation written for the header."""
    spans = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.stmt):
            continue
        body = getattr(node, "body", None)
        if isinstance(body, list) and body \
                and isinstance(body[0], ast.stmt):
            end = max(node.lineno, body[0].lineno - 1)
        else:
            end = getattr(node, "end_lineno", node.lineno)
        spans.append((node.lineno, end))
    spans.sort()
    per_line: Dict[int, set] = {}
    file_level: set = set()
    for i, line in enumerate(sf.lines, 1):
        m = _ALLOW_LINE.search(line)
        if m:
            allowed = {r.strip() for r in m.group(1).split(",")}
        elif _VOLATILE_LINE.search(line):
            allowed = {"snapshot-coverage"}
        else:
            allowed = None
        if allowed:
            per_line.setdefault(i, set()).update(allowed)
            if line.lstrip().startswith("#"):
                # comment-only pragma: cover the next statement's span
                # (an inline pragma covers ONLY its own line — spilling
                # onto the next line would silently waive the rule for
                # an unannotated neighbour)
                nxt = next((s for s in spans if s[0] > i), None)
                cover = (range(nxt[0], nxt[1] + 1) if nxt
                         else range(i + 1, i + 2))
                for ln in cover:
                    per_line.setdefault(ln, set()).update(allowed)
        if i <= _ALLOW_FILE_SCAN_LINES:
            m = _ALLOW_FILE.search(line)
            if m:
                file_level.update(
                    r.strip() for r in m.group(1).split(","))
    return per_line, file_level


class LintResult:
    """Everything one lint run produced, pre-partitioned."""

    def __init__(self, findings, suppressed, baselined, stale_baseline):
        #: unsuppressed, non-baselined findings — the ones that FAIL
        self.findings: List[Finding] = findings
        self.suppressed: List[Finding] = suppressed
        self.baselined: List[Finding] = baselined
        #: baseline entries no longer produced (fixed or drifted) —
        #: informational; --update-baseline clears them
        self.stale_baseline: List[Tuple] = stale_baseline

    @property
    def ok(self) -> bool:
        return not self.findings

    def summary(self) -> str:
        return (f"{len(self.findings)} finding(s), "
                f"{len(self.suppressed)} suppressed, "
                f"{len(self.baselined)} baselined"
                + (f", {len(self.stale_baseline)} stale baseline "
                   f"entr(y/ies)" if self.stale_baseline else ""))


def run_lint(root: Optional[str] = None,
             rules: Sequence[str] = ALL_RULES,
             paths: Optional[Sequence[str]] = None,
             respect_suppressions: bool = True,
             respect_baseline: bool = True,
             files: Optional[Dict[str, SourceFile]] = None) -> LintResult:
    """Run the rule set over the package (or a pre-built ``files``
    mapping for tests). ``paths`` restricts the *reported* findings to
    repo-relative prefixes while still building the call graph over the
    whole package (reachability is a whole-package property)."""
    root = root or repo_root()
    for r in rules:
        if r not in ALL_RULES:
            raise ValueError(f"unknown rule {r!r}; one of {ALL_RULES}")
    if files is None:
        files = package_sources(root)
    # ONE call graph, built once and reused by every rule that needs
    # reachability or import resolution (host-sync/traced-branch jit
    # reachability, the donation rule's cross-module RMW fixpoint); a
    # metric-drift-only run (tests/test_slo.py's delegate) skips the
    # whole-package walk
    if {"host-sync", "traced-branch", "donation"} & set(rules):
        graph = callgraph_mod.build_callgraph(
            {p: sf.tree for p, sf in files.items()})
    else:
        graph = callgraph_mod.CallGraph()
    docs_path = os.path.join(root, "docs", "OBSERVABILITY.md")
    if os.path.exists(docs_path):
        with open(docs_path, encoding="utf-8") as fh:
            docs_text = fh.read()
    else:
        # installed-package run: docs/ is not shipped. An empty docs
        # text would flag EVERY metric/span literal as undocumented —
        # drop both docs-pinned rules instead of failing --check with
        # spurious findings
        docs_text = ""
        rules = tuple(r for r in rules
                      if r not in ("metric-drift", "span-drift"))
    faults_rel = "paddle_tpu/resilience/faults.py"
    fault_sites = (rules_mod.known_fault_sites(files[faults_rel].source)
                   if faults_rel in files else set())
    # the mesh-axis registry: from the files mapping when present
    # (normal runs), else from the tree on disk (synthetic-files test
    # runs); with neither, the axis rules are dropped like metric-drift
    topo_rel = "paddle_tpu/parallel/topology.py"
    topo_disk = os.path.join(root, "paddle_tpu", "parallel",
                             "topology.py")
    if topo_rel in files:
        known_axes = rules_mod.known_mesh_axes(files[topo_rel].source)
    elif os.path.exists(topo_disk):
        with open(topo_disk, encoding="utf-8") as fh:
            known_axes = rules_mod.known_mesh_axes(fh.read())
    else:
        known_axes = {}
        rules = tuple(r for r in rules
                      if r not in ("collective-axis", "pspec-axis"))

    all_findings = rules_mod.run_rules(files, graph, docs_text,
                                       fault_sites, rules=rules,
                                       known_axes=known_axes)
    if paths:
        norm = [p.rstrip("/") for p in paths]
        all_findings = [f for f in all_findings
                        if any(f.path == p or f.path.startswith(p + "/")
                               for p in norm)]

    suppressed: List[Finding] = []
    kept: List[Finding] = []
    if respect_suppressions:
        sup_cache: Dict[str, Tuple[Dict[int, set], set]] = {}
        for f in all_findings:
            if f.path not in sup_cache:
                sup_cache[f.path] = _suppressions(files[f.path])
            per_line, file_level = sup_cache[f.path]
            if f.rule in file_level or f.rule in per_line.get(f.line,
                                                              ()):
                suppressed.append(f)
            else:
                kept.append(f)
    else:
        kept = list(all_findings)

    baselined: List[Finding] = []
    stale: List[Tuple] = []
    if respect_baseline:
        pinned = baseline_mod.load(root)
        kept, baselined, stale = baseline_mod.apply(kept, pinned)
        if paths or set(rules) != set(ALL_RULES):
            # a filtered run sees a SUBSET of findings — out-of-scope
            # pins are not stale, they are merely unobserved
            stale = []
    return LintResult(kept, suppressed, baselined, stale)
