"""The lint baseline: pinned pre-existing violations.

``analysis/baseline.json`` is a checked-in multiset of findings that
predate the linter (schema ``paddle_tpu.lint_baseline/v1``). A
``--check`` run fails only on findings NOT in the pin, so the gate can
land without a flag-day cleanup of every legacy site — while any *new*
sync/branch/dtype regression fails immediately.

Matching is by ``(rule, path, stripped-source-line)`` — not line
number — so unrelated edits that shift a file don't invalidate the
pin; editing the flagged line itself DOES (the site changed; it must
be re-classified: fixed, suppressed with a reason, or re-pinned).

``--update-baseline`` regenerates the file deterministically: findings
sorted by (path, line, rule), repo-relative paths, LF, trailing
newline — two runs over the same tree are byte-identical (pinned by
tests/test_analysis.py).
"""

import json
import os
from collections import Counter
from typing import Dict, List, Optional, Tuple

BASELINE_SCHEMA = "paddle_tpu.lint_baseline/v1"

__all__ = ["BASELINE_SCHEMA", "baseline_path", "load", "apply", "render",
           "write"]


def baseline_path(root: str) -> str:
    return os.path.join(root, "paddle_tpu", "analysis", "baseline.json")


def load(root: str) -> Counter:
    """(rule, path, code) -> pinned count. Missing file = empty pin."""
    path = baseline_path(root)
    if not os.path.exists(path):
        return Counter()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: schema {data.get('schema')!r} != "
            f"{BASELINE_SCHEMA!r}")
    pinned: Counter = Counter()
    for e in data.get("findings", []):
        pinned[(e["rule"], e["path"], e.get("code", ""))] += 1
    return pinned


def apply(findings: List, pinned: Counter
          ) -> Tuple[List, List, List[Tuple]]:
    """Partition ``findings`` into (new, baselined) against the pin and
    report stale pin entries (pinned but no longer produced). Multiset
    semantics: a file with two identical flagged lines needs two pin
    entries — fixing one of them retires one."""
    budget = Counter(pinned)
    new, baselined = [], []
    for f in findings:
        k = f.key()
        if budget[k] > 0:
            budget[k] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale = sorted(budget.elements())
    return new, baselined, stale


def render(findings: List) -> str:
    """Deterministic baseline document for the given findings (which
    should be the run's unsuppressed findings, pre-baseline)."""
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "code": f.code}
               for f in sorted(findings, key=lambda f: f.sort_key())]
    doc = {"schema": BASELINE_SCHEMA,
           "note": ("pre-existing tpu-lint violations; only NEW findings "
                    "fail --check. Regenerate with "
                    "`python -m paddle_tpu.analysis --update-baseline`; "
                    "burn entries down by fixing the site or annotating "
                    "it with `# tpu-lint: allow(<rule>): reason`."),
           "findings": entries}
    return json.dumps(doc, indent=1, sort_keys=False) + "\n"


def write(root: str, findings: List) -> str:
    path = baseline_path(root)
    with open(path, "w", encoding="utf-8", newline="\n") as fh:
        fh.write(render(findings))
    return path
