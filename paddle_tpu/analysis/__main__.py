"""``python -m paddle_tpu.analysis`` — the tpu-lint CLI.

Modes:

* (default) — print every unsuppressed, non-baselined finding.
* ``--check`` — same, but exit 1 if any exist (the tier-1 gate; a
  stale baseline entry is reported but does not fail).
* ``--update-baseline`` — regenerate analysis/baseline.json from the
  current unsuppressed findings (deterministic: sorted,
  path-relative; see analysis/baseline.py).

``--rules r1,r2`` restricts the rule set, ``--paths a b`` restricts
reported findings to repo-relative prefixes, ``--json`` emits a
machine-readable report, ``--show-baselined`` / ``--show-suppressed``
include the pinned/annotated sites in the listing.
"""

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    from paddle_tpu.analysis import baseline as baseline_mod
    from paddle_tpu.analysis import lint

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tpu-lint: static enforcement of the hot-path "
                    "invariants (docs/ANALYSIS.md)")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on any unsuppressed, "
                         "non-baselined finding")
    ap.add_argument("--update-baseline", action="store_true",
                    help="regenerate analysis/baseline.json from the "
                         "current unsuppressed findings")
    ap.add_argument("--rules", default=None,
                    help=f"comma-separated subset of "
                         f"{','.join(lint.ALL_RULES)}")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="repo-relative path prefixes to report on "
                         "(the call graph still spans the package)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--show-baselined", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, pin ignored")
    args = ap.parse_args(argv)

    if args.update_baseline and (args.rules or args.paths):
        # a filtered run sees a SUBSET of findings; writing it would
        # silently erase every other pinned entry and fail the next
        # plain --check on all of them
        ap.error("--update-baseline regenerates the whole pin and "
                 "cannot be combined with --rules/--paths")
    rules = (tuple(r.strip() for r in args.rules.split(","))
             if args.rules else lint.ALL_RULES)
    t0 = time.perf_counter()
    root = lint.repo_root()
    result = lint.run_lint(
        root, rules=rules, paths=args.paths,
        respect_baseline=not (args.no_baseline or args.update_baseline))
    wall = time.perf_counter() - t0

    if args.update_baseline:
        path = baseline_mod.write(root, result.findings)
        print(f"tpu-lint: wrote {len(result.findings)} pinned "
              f"finding(s) to {path}")
        return 0

    if args.json:
        print(json.dumps({
            "findings": [f.to_json() for f in result.findings],
            "suppressed": [f.to_json() for f in result.suppressed],
            "baselined": [f.to_json() for f in result.baselined],
            "stale_baseline": [list(k) for k in result.stale_baseline],
            "wall_s": round(wall, 3)}, indent=1))
    else:
        shown = list(result.findings)
        if args.show_baselined:
            shown += result.baselined
        if args.show_suppressed:
            shown += result.suppressed
        for f in sorted(shown, key=lambda f: f.sort_key()):
            tag = ("" if f in result.findings else
                   " (baselined)" if f in result.baselined
                   else " (suppressed)")
            print(f"{f.path}:{f.line}:{f.col}: [{f.rule}] "
                  f"{f.message}{tag}")
        for key in result.stale_baseline:
            print(f"stale baseline entry (site fixed or moved — rerun "
                  f"--update-baseline): {key[1]}: [{key[0]}] "
                  f"{key[2][:60]}")
        print(f"tpu-lint: {result.summary()} in {wall:.2f}s")
    if args.check and not result.ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
