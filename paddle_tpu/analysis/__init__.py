"""Correctness tooling for the hot-path invariants (docs/ANALYSIS.md).

Two layers:

* **static** — ``tpu-lint`` (:mod:`paddle_tpu.analysis.lint`,
  ``python -m paddle_tpu.analysis``): AST rules for implicit host
  syncs, Python branches on traced values in jit-reachable code,
  float64 defaults in kernel files, metric-name drift vs the docs
  table, unregistered fault sites, state/journal/rng protocol
  coverage, and the mesh/donation rules (``collective-axis`` /
  ``pspec-axis`` pinned against ``parallel.topology.KNOWN_AXES``,
  ``donation`` for undonated RMW carries) — with a checked-in
  baseline and ``# tpu-lint: allow(<rule>)`` inline suppressions.
* **runtime** — the dispatch sanitizer
  (:mod:`paddle_tpu.analysis.runtime`): ``no_transfer`` /
  ``no_recompile`` / ``sanitize`` context guards, wired into
  ``ServingEngine(sanitize=True)`` and the benches' ``--sanitize``;
  ``snapshot_roundtrip`` for the state protocol; ``donation_report``
  for compiled input→output aliasing.

The lint layer never imports jax (it must run in seconds as a tier-1
gate); the runtime layer does. Importing the runtime names through
this package is lazy for that reason.
"""

from paddle_tpu.analysis.lint import (ALL_RULES, Finding, LintResult,
                                      run_lint)

_RUNTIME_NAMES = ("CompileCounter", "DonationError", "DonationReport",
                  "RecompileError", "SnapshotDriftError",
                  "TransferError", "canonical_snapshot",
                  "canonical_snapshot_bytes", "compare_snapshots",
                  "count_compiles", "donation_report", "no_recompile",
                  "no_transfer", "sanitize", "snapshot_roundtrip",
                  "compile_events_supported")

__all__ = ["ALL_RULES", "Finding", "LintResult", "run_lint",
           *_RUNTIME_NAMES]


def __getattr__(name):
    if name in _RUNTIME_NAMES:
        from paddle_tpu.analysis import runtime
        return getattr(runtime, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
