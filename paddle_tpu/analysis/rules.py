"""The tpu-lint rule set — repo-specific hot-path invariants as checks.

Every rule yields :class:`Finding`s; the driver (analysis/lint.py)
applies inline suppressions (``# tpu-lint: allow(<rule>)``) and the
checked-in baseline on top, so a rule is free to be *conservative*
(flag everything that is shaped like a violation) and let intentional
sites be annotated where they live.

Rule catalog (docs/ANALYSIS.md has the workflow):

``host-sync``
    Implicit host synchronization: ``.item()``, ``np.asarray`` /
    ``np.array`` / ``np.ascontiguousarray`` on non-literal arguments
    (a device array operand forces a D2H pull), ``jax.device_get``,
    ``block_until_ready``, and — inside jit-reachable functions only —
    ``float()/int()/bool()`` on array-shaped values (a concretization
    sync under trace). One stray site on the decode hot path regresses
    dispatch latency silently; every intentional site must say why.

``traced-branch``
    Python ``if``/``while``/``assert``/ternary on a value produced by
    a ``jnp``/``lax`` computation inside a function reachable from a
    ``jax.jit``/``pjit`` entry point (analysis/callgraph.py) — under
    trace this is a ConcretizationError at best, a silent
    recompile-per-value at worst. Static extractions (``.shape``,
    ``.ndim``, ``.dtype``, ``len()``, ``is None``) are exempt.

``default-dtype``
    Kernel files (``ops/``, ``inference/``, ``serving/``): numpy array
    creation with the implicit float64/int64 default dtype, and any
    explicit ``float64`` — a float64 operand silently doubles memory
    traffic and detunes TPU-shaped kernels.

``metric-drift``
    Every ``counter/gauge/histogram/sketch("serving.|resilience.|
    decode.*")`` literal in the package must appear in
    docs/OBSERVABILITY.md (the PR 7 drift grep, promoted to a rule —
    tests/test_slo.py delegates here).

``span-drift``
    Every ``serving.``/``decode.`` span-name literal
    (``tracer.span(...)`` / ``tr.record(...)``) must appear in
    docs/OBSERVABILITY.md's span table — metric-drift's twin for the
    tracing plane, so timeline output never carries spans a reader
    cannot look up.

``fault-site``
    ``maybe_fire(...)`` / ``Fault(...)`` site literals must be
    registered in ``resilience.faults.KNOWN_SITES`` — an unregistered
    site is a hook the fault-injection docs and chaos tooling cannot
    see.

``snapshot-coverage``
    The state-protocol audit (docs/SERVING.md §Snapshot contract).
    For every class carrying a snapshot protocol — it defines a save
    method (``snapshot``/``to_config``, or journal-emitting methods)
    AND a load method (``restore``/``recover``) — every MUTABLE
    ``self._x`` assigned in ``__init__`` (mutable = reassigned or
    mutated by another method) must be referenced by both the save and
    load sides, or carry ``# tpu-lint: volatile(reason)``. Asymmetric
    coverage (saved but never restored, or vice versa) is its own
    finding. Owned state classes (``_Slot``) are checked against their
    owner's protocol. "New engine field added, snapshot() silently
    loses it" becomes a lint failure, not a chaos-soak surprise.

``journal-coverage``
    Every terminal request transition in ``serving/`` — a
    ``RequestResult(...)`` construction, a ``results[...]`` store, a
    tick transition-marker append — must live in a function that emits
    a journal event or carries an annotation; every
    ``journal.append("<kind>")`` literal must be registered in
    ``serving.journal.KNOWN_EVENTS``, and every registered kind must
    be emitted somewhere (stale-registry detection). The fault-site
    rule's design, applied to the durability log.

``rng-stream``
    In ``serving/``/``inference/`` (request-serving code), every
    ``jax.random.*`` draw must be keyed by a ``fold_in`` of a request
    stream — locally, via a fold-returning helper, or via a parameter
    whose in-package call sites all pass folded keys (callgraph-
    resolved, with violating CALL SITES flagged). Raw ``PRNGKey`` /
    ``split`` references are findings: an ad-hoc stream in serving
    code silently breaks the batch-composition-invariant sampling
    contract (tests/test_serving.py's parity pins).

``collective-axis``
    Every named-axis collective (``lax.psum``/``pmean``/``pmax``/
    ``pmin``/``ppermute``/``all_gather``/``psum_scatter``/
    ``all_to_all``/``axis_index``/``axis_size``/``pcast``/
    ``pbroadcast`` — 0.9 and jaxcompat-shim spellings alike) whose
    axis-name argument resolves to a string literal (directly, via a
    parameter default, a local assign, or a module constant) must name
    an axis registered in ``parallel.topology.KNOWN_AXES`` — the axis
    set the hybrid mesh can bind and the multichip dryrun validates. A
    typo'd or out-of-registry axis is a lint finding at author time
    instead of an unbound-axis trace error on a v5p mesh. Calls whose
    axis is genuinely dynamic (an un-defaulted parameter) are the
    documented blind spot. ``axis_name=`` keywords on ANY call (the
    ``partial(local, axis_name=...)`` currying sites) are checked too.

``pspec-axis``
    Every ``PartitionSpec`` literal must reference registered axes
    (same registry and same literal resolution as ``collective-axis``);
    where a spec is attached to a statically-known shape
    (``jax.ShapeDtypeStruct((4, 6), ..., sharding=NamedSharding(mesh,
    P("dp", None)))``), each sharded dim must divide by the axis's
    validated degree — the AOT feasibility path fails on indivisible
    dims only at lowering time on the real mesh.

``donation``
    A jitted function whose array argument flows through an RMW chain
    (``x.at[...].set/add``, ``lax.dynamic_update_slice``) into an
    output — directly, through tuple-unpacked aliases, through
    ``lax.scan``/``while_loop``/``fori_loop`` carries, or through
    calls into other package functions (cross-module fixpoint) — must
    donate that argnum, or every dispatch pays a full buffer copy (the
    BENCH_r06 O(prompt²/chunk) carry-copy class). The inverse hazard
    is also flagged: an argument donated at a jit site and then read
    again by the caller after the dispatch is a use-after-free. The
    sanctioned conditional-donation spelling is
    ``inference.carry_donate_argnums(...)`` — the rule reads the
    argnums through it. ``*args``-signature impls whose positions
    can't be mapped are the documented blind spot (the runtime
    ``analysis.runtime.donation_report`` guard covers them).
"""

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set

__all__ = ["Finding", "ALL_RULES", "KERNEL_DIRS", "SNAPSHOT_OWNED",
           "collect_metric_names", "collect_span_names",
           "known_fault_sites",
           "known_journal_events", "known_mesh_axes", "run_rules"]

KERNEL_DIRS = ("paddle_tpu/ops", "paddle_tpu/inference",
               "paddle_tpu/serving")

_NUMPY_CREATORS = {"zeros", "ones", "empty", "full", "arange",
                   "linspace", "eye", "identity"}
_DTYPE_NAMES = {"float32", "float16", "bfloat16", "float64", "int8",
                "int16", "int32", "int64", "uint8", "uint16", "uint32",
                "uint64", "bool_", "complex64", "intp", "float0"}
#: jnp/lax attribute calls that return static METADATA, not traced data
_STATIC_MODULE_CALLS = {"dtype", "issubdtype", "result_type",
                        "promote_types", "iinfo", "finfo", "shape",
                        "ndim", "size"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize",
                 "weak_type", "sharding", "nbytes"}
_TRACED_ROOTS = {"jnp", "lax"}
_TRACED_JAX_SUBMODULES = {"nn", "random", "numpy", "lax", "scipy"}

_METRIC_CALL = re.compile(
    r'(?:counter|gauge|histogram|sketch)\(\s*'
    r'"((?:serving|resilience|decode)\.[a-z0-9_.]+)"')

# span-name literals — Tracer span/record calls whose first argument
# is a ``serving.``/``decode.``-prefixed string: the span-drift rule
# pins every one against the span table in docs/OBSERVABILITY.md,
# exactly like _METRIC_CALL pins metric names
_SPAN_CALL = re.compile(
    r'(?:\.record|\.span|record_span)\(\s*'
    r'"((?:serving|decode)\.[a-z0-9_.]+)"')


class Finding:
    """One lint violation. ``code`` is the stripped source line — the
    baseline matches on (rule, path, code), so findings survive
    unrelated edits that only shift line numbers."""

    __slots__ = ("rule", "path", "line", "col", "message", "code")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, code: str = ""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.code = code

    def key(self):
        return (self.rule, self.path, self.code)

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_json(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "code": self.code}

    def __repr__(self):
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}")


class SourceFile:
    __slots__ = ("path", "source", "lines", "tree")

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node, message: str) -> Finding:
        return Finding(rule, self.path, node.lineno, node.col_offset,
                       message, self.line_text(node.lineno))


# --------------------------------------------------------------- helpers

def _numpy_aliases(tree: ast.Module) -> Set[str]:
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    names.add(a.asname or "numpy")
        elif isinstance(node, ast.ImportFrom) and node.module == "numpy":
            names.add("__from_numpy__")
    return names


def _attr_root(node) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_host_literal(node) -> bool:
    """Arguments that are host data by construction: literals,
    comprehensions, and pure-numpy expressions."""
    if isinstance(node, (ast.Constant, ast.List, ast.Tuple, ast.Dict,
                         ast.ListComp, ast.GeneratorExp, ast.DictComp,
                         ast.SetComp)):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_host_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_host_literal(node.left) and _is_host_literal(node.right)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        # list(...)/sorted(...) results are host objects by construction
        return node.func.id in ("list", "tuple", "sorted", "range")
    return False


def _looks_like_dtype(node) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in _DTYPE_NAMES or node.attr == "dtype"
    if isinstance(node, ast.Name):
        return node.id in _DTYPE_NAMES or "dtype" in node.id.lower()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _DTYPE_NAMES
    if isinstance(node, ast.Call):
        # np.dtype(...), jnp.dtype(...), x.astype's operand etc.
        return (isinstance(node.func, ast.Attribute)
                and node.func.attr == "dtype")
    return False


def _static_extraction(node) -> bool:
    """Expressions whose VALUE is static under trace even when the
    operand is traced: shape/dtype attributes, len(), isinstance(),
    identity comparisons."""
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.Subscript):
        return _static_extraction(node.value)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("len", "isinstance", "hasattr", "getattr",
                                "type")
    return False


def _tainted(node, traced: Set[str]) -> bool:
    """Does this expression's value depend on traced array DATA (as
    opposed to static metadata)?"""
    if node is None or isinstance(node, ast.Constant):
        return False
    if _static_extraction(node):
        return False
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute):
        return _tainted(node.value, traced)
    if isinstance(node, ast.Subscript):
        return _tainted(node.value, traced)
    if isinstance(node, ast.Call):
        root = _attr_root(node.func)
        if root in _TRACED_ROOTS:
            return (not isinstance(node.func, ast.Attribute)
                    or node.func.attr not in _STATIC_MODULE_CALLS)
        if root == "jax" and isinstance(node.func, ast.Attribute):
            # jax.nn.softmax(x) / jax.random.fold_in(...) return traced
            # data; jax.default_backend() and friends do not
            chain = _jax_chain(node.func)
            if len(chain) >= 2 and chain[1] in _TRACED_JAX_SUBMODULES:
                return True
        args = list(node.args) + [kw.value for kw in node.keywords]
        if isinstance(node.func, ast.Attribute) \
                and _tainted(node.func.value, traced):
            return True         # x.astype(...), x.sum() on tainted x
        return any(_tainted(a, traced) for a in args)
    if isinstance(node, ast.BinOp):
        return _tainted(node.left, traced) or _tainted(node.right, traced)
    if isinstance(node, ast.UnaryOp):
        return _tainted(node.operand, traced)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in node.ops):
            return False        # identity / membership: host semantics
        return _tainted(node.left, traced) \
            or any(_tainted(c, traced) for c in node.comparators)
    if isinstance(node, ast.BoolOp):
        return any(_tainted(v, traced) for v in node.values)
    if isinstance(node, ast.IfExp):
        return _tainted(node.body, traced) or _tainted(node.orelse, traced)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_tainted(e, traced) for e in node.elts)
    return False


def _jax_chain(node) -> List[str]:
    chain = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        chain.append(node.id)
    return list(reversed(chain))


class _FuncScoper(ast.NodeVisitor):
    """Shared walk that attributes nodes to their enclosing function's
    qualname (matching analysis/callgraph.py) before dispatching to a
    per-rule ``handle(node, qualname)``."""

    def __init__(self):
        self.stack: List[str] = []

    def _visit_func(self, node):
        self.stack.append(node.name)
        self.enter_function(node, ".".join(self.stack))
        self.generic_visit(node)
        self.exit_function(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def enter_function(self, node, qualname):   # pragma: no cover
        pass

    def exit_function(self, node):              # pragma: no cover
        pass


# ----------------------------------------------------------- host-sync

class _HostSyncVisitor(_FuncScoper):
    def __init__(self, sf: SourceFile, np_aliases: Set[str],
                 is_traced_fn, findings: List[Finding]):
        super().__init__()
        self.sf = sf
        self.np = np_aliases
        self.is_traced_fn = is_traced_fn
        self.findings = findings

    def visit_Call(self, node):
        f = node.func
        sf = self.sf
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args:
                self.findings.append(sf.finding(
                    "host-sync", node,
                    ".item() forces a device sync + D2H scalar pull"))
            elif f.attr == "block_until_ready":
                self.findings.append(sf.finding(
                    "host-sync", node,
                    "block_until_ready blocks the host on device work"))
            elif f.attr == "device_get" and _attr_root(f) == "jax":
                self.findings.append(sf.finding(
                    "host-sync", node,
                    "jax.device_get is an explicit D2H transfer"))
            elif (f.attr in ("asarray", "array", "ascontiguousarray")
                  and isinstance(f.value, ast.Name)
                  and f.value.id in self.np and node.args
                  and not _is_host_literal(node.args[0])
                  and not self._numpy_arg(node.args[0])):
                self.findings.append(sf.finding(
                    "host-sync", node,
                    f"np.{f.attr} on a possibly-device value syncs and "
                    f"copies to host"))
        elif isinstance(f, ast.Name):
            if f.id == "block_until_ready":
                self.findings.append(sf.finding(
                    "host-sync", node,
                    "block_until_ready blocks the host on device work"))
            elif f.id in ("float", "int", "bool") and len(node.args) == 1 \
                    and self._in_traced_function() \
                    and self._concretizes(node.args[0]):
                self.findings.append(sf.finding(
                    "host-sync", node,
                    f"{f.id}() on an array value in jit-reachable code "
                    f"is a concretization sync"))
        self.generic_visit(node)

    def _numpy_arg(self, node) -> bool:
        """np.asarray(np.stack(...)) — already host, not a sync."""
        return (isinstance(node, ast.Call)
                and _attr_root(node.func) in self.np)

    def _in_traced_function(self) -> bool:
        return bool(self.stack) and self.is_traced_fn(
            ".".join(self.stack))

    def _concretizes(self, arg) -> bool:
        """float(x)-style casts that force a device value concrete:
        calls and subscripts of non-static expressions. Plain names and
        static metadata (shape/len/...) stay un-flagged — config casts
        are the common benign case."""
        if _static_extraction(arg) or isinstance(arg, (ast.Constant,
                                                       ast.Name,
                                                       ast.Attribute)):
            # plain names and attribute reads are the benign config-cast
            # case; only value-producing expressions (calls, subscripts)
            # can force a device array concrete
            return False
        if isinstance(arg, (ast.Call, ast.Subscript)):
            return not _static_extraction(arg)
        if isinstance(arg, ast.BinOp):
            return self._concretizes(arg.left) \
                or self._concretizes(arg.right)
        if isinstance(arg, ast.UnaryOp):
            return self._concretizes(arg.operand)
        return False


def check_host_sync(sf: SourceFile, graph) -> List[Finding]:
    module = _module_name(sf.path)
    findings: List[Finding] = []
    v = _HostSyncVisitor(
        sf, _numpy_aliases(sf.tree),
        lambda qual: graph.is_traced(module, qual), findings)
    v.visit(sf.tree)
    return findings


# -------------------------------------------------------- traced-branch

class _TracedBranchVisitor(_FuncScoper):
    def __init__(self, sf: SourceFile, is_traced_fn,
                 findings: List[Finding]):
        super().__init__()
        self.sf = sf
        self.is_traced_fn = is_traced_fn
        self.findings = findings
        self.traced_vars: List[Set[str]] = []

    def enter_function(self, node, qualname):
        # locals assigned from jnp/lax computations are traced values;
        # two forward passes so `y = x + 1` after `x = jnp.sum(...)`
        # taints even with one-pass visiting order quirks
        traced: Set[str] = set()
        for _ in range(2):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and _tainted(sub.value,
                                                            traced):
                    for t in sub.targets:
                        self._taint_target(t, traced)
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)) \
                        and sub.value is not None \
                        and _tainted(sub.value, traced):
                    self._taint_target(sub.target, traced)
        self.traced_vars.append(traced)

    def exit_function(self, node):
        self.traced_vars.pop()

    @staticmethod
    def _taint_target(t, traced: Set[str]):
        if isinstance(t, ast.Name):
            traced.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                _TracedBranchVisitor._taint_target(e, traced)

    def _check_test(self, test, what: str):
        if not self.traced_vars or not self.stack:
            return
        if not self.is_traced_fn(".".join(self.stack)):
            return
        if _tainted(test, self.traced_vars[-1]):
            self.findings.append(self.sf.finding(
                "traced-branch", test,
                f"Python {what} on a traced value in jit-reachable "
                f"code — use lax.cond/jnp.where or hoist the check"))

    def visit_If(self, node):
        self._check_test(node.test, "branch")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_test(node.test, "while-loop")
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._check_test(node.test, "conditional expression")
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._check_test(node.test, "assert")
        self.generic_visit(node)


def check_traced_branch(sf: SourceFile, graph) -> List[Finding]:
    module = _module_name(sf.path)
    findings: List[Finding] = []
    v = _TracedBranchVisitor(
        sf, lambda qual: graph.is_traced(module, qual), findings)
    v.visit(sf.tree)
    return findings


# -------------------------------------------------------- default-dtype

class _DefaultDtypeVisitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, np_aliases: Set[str],
                 findings: List[Finding]):
        self.sf = sf
        self.np = np_aliases
        self.findings = findings

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in self.np:
            if f.attr in _NUMPY_CREATORS:
                has_dtype = any(kw.arg == "dtype" for kw in node.keywords) \
                    or any(_looks_like_dtype(a) for a in node.args)
                if not has_dtype:
                    self.findings.append(self.sf.finding(
                        "default-dtype", node,
                        f"np.{f.attr} without an explicit dtype defaults "
                        f"to float64/int64 in kernel code"))
                for a in node.args:
                    # a POSITIONAL float64 dtype must not escape just
                    # because it satisfied has_dtype
                    if self._is_float64(a):
                        self.findings.append(self.sf.finding(
                            "default-dtype", a,
                            "explicit float64 dtype in kernel code"))
            elif f.attr == "float64":
                self.findings.append(self.sf.finding(
                    "default-dtype", node,
                    "explicit float64 scalar in kernel code"))
            elif f.attr in ("asarray", "array") and node.args:
                for a in node.args[1:]:     # positional dtype
                    if self._is_float64(a):
                        self.findings.append(self.sf.finding(
                            "default-dtype", a,
                            "explicit float64 dtype in kernel code"))
                if self._bare_float_literal(node.args[0]) \
                        and not any(kw.arg == "dtype"
                                    for kw in node.keywords) \
                        and not any(_looks_like_dtype(a)
                                    for a in node.args[1:]):
                    self.findings.append(self.sf.finding(
                        "default-dtype", node,
                        "bare float literal arrayified at float64"))
        for kw in getattr(node, "keywords", []):
            if kw.arg == "dtype" and self._is_float64(kw.value):
                self.findings.append(self.sf.finding(
                    "default-dtype", kw.value,
                    "explicit float64 dtype in kernel code"))
        self.generic_visit(node)

    @staticmethod
    def _is_float64(node) -> bool:
        if isinstance(node, ast.Attribute):
            return node.attr == "float64"
        if isinstance(node, ast.Constant):
            return node.value in ("float64", "double")
        return False

    @staticmethod
    def _bare_float_literal(node) -> bool:
        """A float scalar, or a list/tuple literal containing one —
        numpy infers float64 for both."""
        if isinstance(node, ast.Constant):
            return isinstance(node.value, float)
        if isinstance(node, (ast.List, ast.Tuple)):
            return any(_DefaultDtypeVisitor._bare_float_literal(e)
                       for e in node.elts)
        return False


def check_default_dtype(sf: SourceFile, graph=None) -> List[Finding]:
    norm = sf.path.replace(os.sep, "/")
    if not any(norm.startswith(d + "/") or os.path.dirname(norm) == d
               for d in KERNEL_DIRS):
        return []
    findings: List[Finding] = []
    _DefaultDtypeVisitor(sf, _numpy_aliases(sf.tree) | {"np"},
                         findings).visit(sf.tree)
    return findings


# --------------------------------------------------------- metric-drift

def collect_metric_names(sources: Dict[str, str]) -> Dict[str, List]:
    """name -> [(path, line)] for every serving./resilience./decode.*
    metric literal created in the package. The ONE implementation both
    the lint rule and tests/test_slo.py use. Scans whole files (the
    ``\\s*`` crosses newlines), so a call wrapped for line length is
    still seen."""
    names: Dict[str, List] = {}
    for path, src in sources.items():
        for m in _METRIC_CALL.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            names.setdefault(m.group(1), []).append((path, line))
    return names


def check_metric_drift(sources: Dict[str, str], docs_text: str,
                       line_lookup) -> List[Finding]:
    findings = []
    for name, sites in sorted(collect_metric_names(sources).items()):
        if name in docs_text:
            continue
        for path, line in sites:
            findings.append(Finding(
                "metric-drift", path, line, 0,
                f"metric {name!r} is not documented in "
                f"docs/OBSERVABILITY.md", line_lookup(path, line)))
    return findings


# ----------------------------------------------------------- span-drift

def collect_span_names(sources: Dict[str, str]) -> Dict[str, List]:
    """name -> [(path, line)] for every ``serving.``/``decode.`` span
    literal created in the package (``tracer.span("...")`` /
    ``tr.record("...")``). The span twin of
    :func:`collect_metric_names` — whole-file scan, wrapped calls
    included."""
    names: Dict[str, List] = {}
    for path, src in sources.items():
        for m in _SPAN_CALL.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            names.setdefault(m.group(1), []).append((path, line))
    return names


def check_span_drift(sources: Dict[str, str], docs_text: str,
                     line_lookup) -> List[Finding]:
    """Every span-name literal must appear in docs/OBSERVABILITY.md's
    span table — an undocumented span is timeline output a reader
    cannot interpret, the exact drift metric-drift catches for metric
    names."""
    findings = []
    for name, sites in sorted(collect_span_names(sources).items()):
        if name in docs_text:
            continue
        for path, line in sites:
            findings.append(Finding(
                "span-drift", path, line, 0,
                f"span {name!r} is not documented in "
                f"docs/OBSERVABILITY.md", line_lookup(path, line)))
    return findings


# ----------------------------------------------------------- fault-site

def known_fault_sites(faults_source: str) -> Set[str]:
    """Parse resilience/faults.py for the KNOWN_SITES literal — the
    linter must not import the package (no jax import on the lint
    path)."""
    tree = ast.parse(faults_source)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "KNOWN_SITES":
                    return {e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)}
    return set()


def check_fault_site(sf: SourceFile, sites: Set[str]) -> List[Finding]:
    if sf.path.replace(os.sep, "/").endswith("resilience/faults.py"):
        return []       # the registry itself (defaults, docstrings)
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name not in ("maybe_fire", "Fault"):
            continue
        site = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            site = node.args[0].value
        else:
            for kw in node.keywords:
                if kw.arg == "site" and isinstance(kw.value, ast.Constant):
                    site = kw.value.value
        if site is not None and site not in sites:
            findings.append(sf.finding(
                "fault-site", node,
                f"fault site {site!r} is not registered in "
                f"resilience.faults.KNOWN_SITES"))
    return findings


# ---------------------------------------------------- snapshot-coverage

#: method names that SAVE a class's state / LOAD it back
_SAVE_METHOD_NAMES = ("snapshot", "to_config")
_LOAD_METHOD_NAMES = ("restore", "recover")
#: methods whose self-stores do NOT make a field "mutable runtime
#: state": construction, teardown, and the protocol methods themselves
_MUTABILITY_EXEMPT = {"__init__", "close", "__exit__"}
#: method calls that mutate their receiver in place — self._queue.push,
#: self._open.add, self.prefix_cache.insert are state mutations even
#: though no attribute store appears
_MUTATOR_CALLS = {"append", "appendleft", "add", "insert", "update",
                  "pop", "popleft", "push", "remove", "discard",
                  "clear", "extend", "setdefault", "free"}
#: state classes with no protocol of their own whose fields ride an
#: owner's snapshot/restore (same file): owner class name per state
#: class. The engine serializes _Slot state as resumable requests.
SNAPSHOT_OWNED = {"_Slot": "ServingEngine"}


def _store_target_attr(node, receiver: Optional[str] = "self"):
    """The attribute name a store targets, peeling subscripts:
    ``self.x = / self.x[i] = / self.x[i][:] =`` all mutate ``x``.
    ``receiver=None`` matches any simple-name receiver."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and (receiver is None or node.value.id == receiver):
        return node.attr
    return None


def _mutated_attrs(fn, receiver="self") -> Set[str]:
    """Attribute names this function mutates on ``receiver``: direct /
    subscript / augmented stores plus in-place mutator calls."""
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                elts = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                        else [t])
                for e in elts:
                    a = _store_target_attr(e, receiver)
                    if a:
                        out.add(a)
        elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
            a = _store_target_attr(sub.target, receiver)
            if a:
                out.add(a)
        elif isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _MUTATOR_CALLS:
            a = _store_target_attr(sub.func.value, receiver)
            if a:
                out.add(a)
    return out


def _name_refs(fns) -> Set[str]:
    """Every attribute name and string constant referenced in the given
    function bodies — the (deliberately generous) "this side of the
    protocol mentions the field" test. Engine fields are matched by
    attribute reads (``self._seeds_issued``), owned-class fields by the
    serialized dict keys (``rs["tokens"]``)."""
    names: Set[str] = set()
    for fn in fns:
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Attribute):
                names.add(sub.attr)
            elif isinstance(sub, ast.Constant) \
                    and isinstance(sub.value, str):
                names.add(sub.value)
    return names


def _init_fields(init) -> Dict[str, ast.stmt]:
    """attr -> FIRST ``self.x = ...`` statement in ``__init__`` (the
    line findings anchor to and ``volatile(...)`` pragmas annotate)."""
    fields: Dict[str, ast.stmt] = {}
    if init is None:
        return fields
    for sub in ast.walk(init):
        targets = []
        if isinstance(sub, ast.Assign):
            targets = sub.targets
        elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
            targets = [sub.target]
        for t in targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                fields.setdefault(t.attr, sub)
    return fields


def _journal_emitters(methods: Dict[str, ast.FunctionDef]) -> List[str]:
    """Methods (other than __init__) containing a journal append — the
    Router's save side IS its journal writes."""
    out = []
    for name, fn in methods.items():
        if name == "__init__":
            continue
        if any(_journal_append_kind(sub) is not _NOT_JOURNAL
               for sub in ast.walk(fn) if isinstance(sub, ast.Call)):
            out.append(name)
    return out


def _class_methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def check_snapshot_coverage(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    classes = {n.name: n for n in ast.walk(sf.tree)
               if isinstance(n, ast.ClassDef)}
    protocols = {}      # class name -> (save fns, load fns, methods)
    for cname, cls in classes.items():
        methods = _class_methods(cls)
        save = [methods[n] for n in _SAVE_METHOD_NAMES if n in methods]
        save += [methods[n] for n in _journal_emitters(methods)
                 if methods[n] not in save]
        load = [methods[n] for n in _LOAD_METHOD_NAMES if n in methods]
        if "to_config" in methods and "__init__" in methods \
                and not load:
            # the SpecConfig pattern: to_config() round-trips through
            # the constructor (restore does SpecConfig(**cfg))
            load = [methods["__init__"]]
        if save and load:
            protocols[cname] = (save, load, methods)

    def _fmt(fns):
        names = sorted({f.name for f in fns})
        if len(names) > 3:
            return f"{names[0]}, {names[1]} (+{len(names) - 2} more)"
        return ", ".join(names)

    def _audit(fields, mutated, save, load, what, volatile_hint):
        save_refs = _name_refs(save)
        load_refs = _name_refs(load)
        save_names = _fmt(save)
        load_names = _fmt(load)
        for attr in sorted(fields):
            if attr not in mutated:
                continue        # assigned once at construction: config
            node = fields[attr]
            saved = attr in save_refs or attr.lstrip("_") in save_refs
            loaded = attr in load_refs or attr.lstrip("_") in load_refs
            if saved and loaded:
                continue
            if saved:
                findings.append(sf.finding(
                    "snapshot-coverage", node,
                    f"{what}.{attr} is saved by {save_names}() but "
                    f"never restored by {load_names}() — asymmetric "
                    f"snapshot coverage"))
            elif loaded:
                findings.append(sf.finding(
                    "snapshot-coverage", node,
                    f"{what}.{attr} is restored by {load_names}() but "
                    f"never saved by {save_names}() — asymmetric "
                    f"snapshot coverage"))
            else:
                findings.append(sf.finding(
                    "snapshot-coverage", node,
                    f"{what}.{attr} is mutable state not covered by "
                    f"the snapshot protocol: serialize it in "
                    f"{save_names}() + {load_names}(), or annotate "
                    f"{volatile_hint}"))

    for cname, (save, load, methods) in protocols.items():
        fields = _init_fields(methods.get("__init__"))
        if "__init__" in [f.name for f in load]:
            # to_config-style: a field is loaded iff the constructor
            # takes it back as a parameter
            load_params = set()
            for fn in load:
                a = fn.args
                load_params |= {p.arg for p in (a.posonlyargs + a.args
                                                + a.kwonlyargs)}
            mutated = set()
        else:
            load_params = set()
            exempt = _MUTABILITY_EXEMPT \
                | {f.name for f in save} | {f.name for f in load}
            mutated = set()
            for mname, fn in methods.items():
                if mname not in exempt:
                    mutated |= _mutated_attrs(fn)
        if load_params:
            # to_config classes: flag fields that don't round-trip
            save_refs = _name_refs(save)
            for attr in sorted(fields):
                if attr in load_params:
                    continue
                if attr in save_refs:
                    continue    # serialized but constructor-external
                findings.append(sf.finding(
                    "snapshot-coverage", fields[attr],
                    f"{cname}.{attr} does not round-trip through "
                    f"to_config() -> __init__(**cfg)"))
            continue
        _audit(fields, mutated, save, load, cname,
               "`# tpu-lint: volatile(reason)`")

    # owned state classes ride their owner's protocol: their fields
    # must appear in the owner's save AND load bodies (serialized dict
    # keys count), or be annotated volatile at their __init__ line
    for owned_name, owner_name in sorted(SNAPSHOT_OWNED.items()):
        if owned_name not in classes or owner_name not in protocols:
            continue
        save, load, owner_methods = protocols[owner_name]
        owned_methods = _class_methods(classes[owned_name])
        fields = _init_fields(owned_methods.get("__init__"))
        exempt = _MUTABILITY_EXEMPT \
            | {f.name for f in save} | {f.name for f in load}
        mutated = set()
        for mname, fn in owner_methods.items():
            if mname not in exempt:
                # stores on any receiver: the owner mutates slot
                # objects through locals (s.pos = ..., s.tokens.append)
                mutated |= _mutated_attrs(fn, receiver=None)
        _audit(fields, mutated, save, load, owned_name,
               "`# tpu-lint: volatile(reason)`")
    return findings


# ----------------------------------------------------- journal-coverage

_JOURNAL_SCOPE = "paddle_tpu/serving/"
_JOURNAL_REGISTRY_PATH = "paddle_tpu/serving/journal.py"
#: the engine's per-tick transition markers: an append to one IS a
#: request-state transition site (preempt/resume/retire/shed/finish)
_TRANSITION_MARKERS = {"_tick_preempted", "_tick_resumed",
                       "_tick_retired", "_tick_shed", "_finished_tick",
                       "_pending_finished"}
_NOT_JOURNAL = object()


def _journal_append_kind(call: ast.Call):
    """For ``<...journal...>.append(kind, ...)`` calls: the kind (a str
    literal, or None for a non-literal kind). ``_NOT_JOURNAL`` for any
    other call. The receiver chain must mention "journal" so list
    appends and the tick markers never match."""
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr != "append":
        return _NOT_JOURNAL
    node, mentions = f.value, False
    while isinstance(node, ast.Attribute):
        mentions = mentions or "journal" in node.attr
        node = node.value
    if isinstance(node, ast.Name):
        mentions = mentions or "journal" in node.id
    if not mentions:
        return _NOT_JOURNAL
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def known_journal_events(journal_source: str) -> Set[str]:
    """Parse serving/journal.py for the KNOWN_EVENTS literal (dict or
    tuple) without importing it — no jax on the lint path."""
    tree = ast.parse(journal_source)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "KNOWN_EVENTS":
                    v = node.value
                    if isinstance(v, ast.Dict):
                        return {k.value for k in v.keys
                                if isinstance(k, ast.Constant)}
                    if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                        return {e.value for e in v.elts
                                if isinstance(e, ast.Constant)}
    return set()


class _JournalVisitor(_FuncScoper):
    def __init__(self, sf: SourceFile, events: Set[str],
                 findings: List[Finding], emitted: Set[str]):
        super().__init__()
        self.sf = sf
        self.events = events
        self.findings = findings
        self.emitted = emitted
        # per function-frame: (anchor nodes, emits journal?)
        self.frames: List = [[[], False]]

    def enter_function(self, node, qualname):
        self.frames.append([[], False])

    def exit_function(self, node):
        anchors, emits = self.frames.pop()
        if emits or not anchors:
            return
        # ONE finding per transition function, anchored at its first
        # transition statement — the site is the function, and one
        # annotation should classify it
        self.findings.append(self.sf.finding(
            "journal-coverage", anchors[0],
            f"terminal request transition in "
            f"{'.'.join(self.stack) or '<module>'} emits no "
            f"journal event — journal it (a KNOWN_EVENTS kind) or "
            f"annotate why the protocol covers it elsewhere"))

    def _anchor(self, node):
        self.frames[-1][0].append(node)

    def visit_Call(self, node):
        kind = _journal_append_kind(node)
        if kind is not _NOT_JOURNAL:
            self.frames[-1][1] = True
            if kind is None:
                self.findings.append(self.sf.finding(
                    "journal-coverage", node,
                    "journal event kind must be a string literal so "
                    "the registry pin can see it"))
            else:
                self.emitted.add(kind)
                if kind not in self.events:
                    self.findings.append(self.sf.finding(
                        "journal-coverage", node,
                        f"journal event {kind!r} is not registered in "
                        f"serving.journal.KNOWN_EVENTS"))
        else:
            f = node.func
            name = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else None)
            if name == "RequestResult":
                self._anchor(node)
            elif isinstance(f, ast.Attribute) \
                    and f.attr == "append" \
                    and _store_target_attr(f.value, None) \
                    in _TRANSITION_MARKERS:
                self._anchor(node)
        self.generic_visit(node)

    def _check_store(self, target):
        if isinstance(target, ast.Subscript) \
                and _store_target_attr(target, None) == "results":
            self._anchor(target)

    def visit_Assign(self, node):
        for t in node.targets:
            self._check_store(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_store(node.target)
        self.generic_visit(node)

    def exit_module(self):
        anchors, emits = self.frames[0]
        if anchors and not emits:
            self.findings.append(self.sf.finding(
                "journal-coverage", anchors[0],
                "terminal request transition at module level emits "
                "no journal event"))


def check_journal_coverage(files: Dict[str, "SourceFile"]
                           ) -> List[Finding]:
    reg_sf = files.get(_JOURNAL_REGISTRY_PATH)
    events = (known_journal_events(reg_sf.source)
              if reg_sf is not None else set())
    findings: List[Finding] = []
    emitted: Set[str] = set()
    for path, sf in files.items():
        if not path.startswith(_JOURNAL_SCOPE) \
                or path == _JOURNAL_REGISTRY_PATH:
            continue
        v = _JournalVisitor(sf, events, findings, emitted)
        v.visit(sf.tree)
        v.exit_module()
    if reg_sf is not None:
        for kind in sorted(events - emitted):
            # anchor at the KNOWN_EVENTS entry so the finding names the
            # rotting registry line
            line = next((i for i, text in enumerate(reg_sf.lines, 1)
                         if f'"{kind}"' in text), 1)
            findings.append(Finding(
                "journal-coverage", reg_sf.path, line, 0,
                f"KNOWN_EVENTS kind {kind!r} is registered but never "
                f"emitted anywhere in serving/ — stale registry entry",
                reg_sf.line_text(line)))
    return findings


# ---------------------------------------------------------- rng-stream

_RNG_SCOPE = ("paddle_tpu/serving/", "paddle_tpu/inference/")
#: jax.random samplers whose first argument is a PRNG key
_RANDOM_DRAWS = {"categorical", "uniform", "normal", "gumbel",
                 "bernoulli", "randint", "truncated_normal",
                 "exponential", "choice", "permutation", "laplace",
                 "logistic", "beta", "gamma", "poisson", "rademacher",
                 "dirichlet", "shuffle"}
#: raw stream constructors: creating/forking a stream in serving code
#: is the finding — request code derives keys via fold_in
_RAW_STREAMS = {"PRNGKey", "split", "key"}


def _is_jax_random(node, random_aliases: Set[str]):
    """(kind, name) when ``node`` references jax.random.<name> — via
    the attribute chain or a from-import alias; (None, None) else."""
    if isinstance(node, ast.Attribute):
        base = node.value
        chain = []
        while isinstance(base, ast.Attribute):
            chain.append(base.attr)
            base = base.value
        if isinstance(base, ast.Name):
            chain.append(base.id)
        if "random" in chain:
            return ("attr", node.attr)
    if isinstance(node, ast.Name) and node.id in random_aliases:
        return ("name", node.id)
    return (None, None)


def _random_from_imports(tree: ast.Module) -> Set[str]:
    """Local names bound by ``from jax.random import X [as y]``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module == "jax.random":
            for a in node.names:
                out.add(a.asname or a.name)
    return out


class _RngFuncInfo:
    """One function's rng-relevant facts, kept for the cross-function
    call-site pass."""

    __slots__ = ("sf", "qualname", "params", "folded", "param_draws",
                 "calls")

    def __init__(self, sf, qualname, params):
        self.sf = sf
        self.qualname = qualname
        self.params = params            # name -> position
        self.folded: Set[str] = set()   # locals carrying folded keys
        self.param_draws: List = []     # (param_name, draw node)
        self.calls: List = []           # (callee name, call node)


def _expr_is_folded(node, folded_vars: Set[str],
                    folding_fns: Set[str]) -> bool:
    """Does this expression derive from a fold_in? True when any node
    within it references ``fold_in`` (jax.random.fold_in, vmapped or
    not), calls a known fold-returning helper, or reads a local already
    carrying a folded key."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "fold_in":
            return True
        if isinstance(sub, ast.Name) and (sub.id == "fold_in"
                                          or sub.id in folded_vars):
            return True
        if isinstance(sub, ast.Call):
            f = sub.func
            callee = (f.id if isinstance(f, ast.Name)
                      else f.attr if isinstance(f, ast.Attribute)
                      else None)
            if callee in folding_fns:
                return True
    return False


def _fn_params(args: ast.arguments) -> Dict[str, int]:
    params = {}
    for i, p in enumerate(args.posonlyargs + args.args):
        params[p.arg] = i
    for p in args.kwonlyargs:
        params[p.arg] = -1
    return params


class _RngVisitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, random_aliases: Set[str],
                 folding_fns: Set[str], infos: Dict[str, "_RngFuncInfo"],
                 findings: List[Finding]):
        self.sf = sf
        self.aliases = random_aliases
        self.folding_fns = folding_fns
        self.infos = infos
        self.findings = findings
        self.stack: List[_RngFuncInfo] = []
        self.qual: List[str] = []
        # Lambda node -> positional application args (the
        # ``jax.vmap(lambda k, ...)(key, ...)`` pattern): a draw keyed
        # by a lambda param resolves through the applied argument. The
        # application Call is visited BEFORE the Lambda it contains, so
        # the mapping exists when the lambda frame is pushed.
        self.lambda_apps: Dict[int, List] = {}
        self.lambda_frames: List = []   # (params, applied args or None)

    # ------------------------------------------------------------ defs
    def _visit_func(self, node):
        self.qual.append(node.name)
        info = _RngFuncInfo(self.sf, ".".join(self.qual),
                            _fn_params(node.args))
        # two-pass local taint: locals assigned from folded expressions
        for _ in range(2):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and _expr_is_folded(
                        sub.value, info.folded, self.folding_fns):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            info.folded.add(t.id)
                        elif isinstance(t, (ast.Tuple, ast.List)):
                            for e in t.elts:
                                if isinstance(e, ast.Name):
                                    info.folded.add(e.id)
        self.infos.setdefault(node.name, []).append(info)
        self.stack.append(info)
        self.generic_visit(node)
        self.stack.pop()
        self.qual.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self.qual.append(node.name)
        self.generic_visit(node)
        self.qual.pop()

    def visit_Lambda(self, node):
        self.lambda_frames.append((_fn_params(node.args),
                                   self.lambda_apps.get(id(node))))
        self.generic_visit(node)
        self.lambda_frames.pop()

    # ----------------------------------------------------------- calls
    def visit_Call(self, node):
        # record lambda applications: (vmap-ish(lambda ...))(args) or
        # (lambda ...)(args) — maps lambda params to applied exprs
        if isinstance(node.func, ast.Lambda):
            self.lambda_apps[id(node.func)] = list(node.args)
        elif isinstance(node.func, ast.Call):
            for a in node.func.args:
                if isinstance(a, ast.Lambda):
                    self.lambda_apps[id(a)] = list(node.args)
        kind, name = _is_jax_random(node.func, self.aliases)
        if kind and name in _RANDOM_DRAWS:
            self._check_draw(node)
        elif self.stack:
            f = node.func
            callee = (f.id if isinstance(f, ast.Name)
                      else f.attr if isinstance(f, ast.Attribute)
                      else None)
            if callee is not None:
                self.stack[-1].calls.append((callee, node))
        self.generic_visit(node)

    def visit_Attribute(self, node):
        self._check_raw(node)
        self.generic_visit(node)

    def visit_Name(self, node):
        self._check_raw(node)
        self.generic_visit(node)

    def _check_raw(self, node):
        kind, name = _is_jax_random(node, self.aliases)
        if name in _RAW_STREAMS and isinstance(getattr(
                node, "ctx", None), ast.Load):
            # flag the OUTERMOST reference only (jax.random.PRNGKey is
            # one finding, not one per chain link); Name hits only for
            # from-imports
            if kind == "attr" or (kind == "name"
                                  and name in self.aliases):
                self.findings.append(self.sf.finding(
                    "rng-stream", node,
                    f"raw jax.random.{name} in request-serving code — "
                    f"derive per-request keys via fold_in (or annotate "
                    f"the sanctioned base-key builder)"))

    def _key_expr(self, node: ast.Call):
        if node.args:
            return node.args[0]
        for kw in node.keywords:
            if kw.arg == "key":
                return kw.value
        return None

    def _check_draw(self, node: ast.Call):
        key = self._key_expr(node)
        info = self.stack[-1] if self.stack else None
        folded = info.folded if info else set()
        if key is None or _expr_is_folded(key, folded,
                                          self.folding_fns):
            return
        if isinstance(key, ast.Name):
            # a lambda param resolves through its application site:
            # ``jax.vmap(lambda k, lg: draw(k, lg))(key, logits)``
            # draws from whatever was applied at k's position
            for params, applied in reversed(self.lambda_frames):
                if key.id in params:
                    pos = params[key.id]
                    if applied is None or not 0 <= pos < len(applied):
                        return          # unapplied lambda: blind spot
                    key = applied[pos]
                    break
        if _expr_is_folded(key, folded, self.folding_fns):
            return
        if isinstance(key, ast.Name) and info is not None \
                and key.id in info.params:
            info.param_draws.append((key.id, node))
            return
        self.findings.append(self.sf.finding(
            "rng-stream", node,
            "jax.random draw keyed by a non-fold_in stream — request-"
            "serving draws must fold a request seed (fold_in(key, t))"))


def check_rng_stream(files: Dict[str, "SourceFile"]) -> List[Finding]:
    scope = {p: sf for p, sf in files.items()
             if p.startswith(_RNG_SCOPE)}
    findings: List[Finding] = []
    # fold-returning helpers, by bare name across the scope: a function
    # whose body references fold_in returns folded keys (_fold_rows)
    folding_fns: Set[str] = set()
    for sf in scope.values():
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(isinstance(s, ast.Attribute)
                       and s.attr == "fold_in"
                       or isinstance(s, ast.Name) and s.id == "fold_in"
                       for s in ast.walk(node)):
                    folding_fns.add(node.name)
    infos: Dict[str, List[_RngFuncInfo]] = {}
    for sf in scope.values():
        v = _RngVisitor(sf, _random_from_imports(sf.tree), folding_fns,
                        infos, findings)
        v.visit(sf.tree)
    # cross-function pass: a function drawing from its own parameter is
    # fine IFF every in-scope call site passes a folded key (or its own
    # parameter, which propagates the obligation) — flag the call site
    forwarding: Dict[str, Set[int]] = {}    # fn name -> key positions
    for name, fn_infos in infos.items():
        for info in fn_infos:
            for pname, _ in info.param_draws:
                pos = info.params.get(pname, -1)
                if pos >= 0:
                    forwarding.setdefault(name, set()).add(pos)
    changed = True
    flagged: Set[int] = set()
    while changed:
        changed = False
        for fn_infos in infos.values():
            for info in fn_infos:
                for callee, call in info.calls:
                    for pos in forwarding.get(callee, ()):
                        if pos >= len(call.args):
                            continue
                        arg = call.args[pos]
                        if isinstance(arg, ast.Constant) \
                                and arg.value is None:
                            continue    # key=None: greedy, no draw
                        if _expr_is_folded(arg, info.folded,
                                           folding_fns):
                            continue
                        if isinstance(arg, ast.Name) \
                                and arg.id in info.params:
                            p = info.params[arg.id]
                            name = info.qualname.rsplit(".", 1)[-1]
                            if p >= 0 and p not in forwarding.get(
                                    name, set()):
                                forwarding.setdefault(name,
                                                      set()).add(p)
                                changed = True
                            continue
                        if id(call) not in flagged:
                            flagged.add(id(call))
                            findings.append(info.sf.finding(
                                "rng-stream", call,
                                f"passes a non-fold_in key into "
                                f"{callee}(), which draws from it — "
                                f"fold a request seed at this call "
                                f"site"))
    return findings


# ------------------------------------------- mesh-axis literal support

def known_mesh_axes(topology_source: str) -> Dict[str, Optional[int]]:
    """Parse parallel/topology.py for the KNOWN_AXES dict literal —
    axis name -> validated degree (or None) — without importing the
    package (no jax on the lint path)."""
    tree = ast.parse(topology_source)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "KNOWN_AXES" \
                    and isinstance(node.value, ast.Dict):
                out: Dict[str, Optional[int]] = {}
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        out[k.value] = (v.value if isinstance(
                            v, ast.Constant)
                            and isinstance(v.value, int) else None)
                return out
    return {}


class _AxisScopes:
    """Literal resolution for axis-name expressions: a Name resolves
    through the enclosing function's parameter defaults and local
    string assigns, then module-level string constants. Returns the
    resolved string or None (dynamic — the documented blind spot)."""

    def __init__(self, tree: ast.Module):
        self.module_consts: Dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_consts[t.id] = node.value.value
        self.stack: List[Dict[str, str]] = []

    @staticmethod
    def _fn_scope(node) -> Dict[str, str]:
        scope: Dict[str, str] = {}
        a = node.args
        pos = a.posonlyargs + a.args
        for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
            if isinstance(d, ast.Constant) and isinstance(d.value, str):
                scope[p.arg] = d.value
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if isinstance(d, ast.Constant) and isinstance(d.value, str):
                scope[p.arg] = d.value
        # shallow: a nested function's locals shadow — they are pushed
        # as their own frame when the scoper enters them, and must not
        # leak into (or override) the enclosing scope here. Assigns
        # apply in TEXT order (the shallow walk's stack order is not
        # source order), so `ax = 'tmp'; ax = 'mp'` resolves to 'mp' —
        # the value in effect at any later call site
        assigns = [s for s in _walk_shallow(node)
                   if isinstance(s, ast.Assign)
                   and isinstance(s.value, ast.Constant)
                   and isinstance(s.value.value, str)]
        for s in sorted(assigns, key=lambda s: s.lineno):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    scope[t.id] = s.value.value
        return scope

    def push(self, node):
        self.stack.append(self._fn_scope(node))

    def pop(self):
        self.stack.pop()

    def resolve_name(self, name: str) -> Optional[str]:
        for scope in reversed(self.stack):
            if name in scope:
                return scope[name]
        return self.module_consts.get(name)

    def axis_literals(self, node) -> List[str]:
        """Every axis-name string this expression statically resolves
        to: a constant, a tuple/list of constants, or resolvable
        Names. Dynamic parts resolve to nothing (never a false
        positive from an unresolvable expression)."""
        if isinstance(node, ast.Constant):
            return ([node.value] if isinstance(node.value, str) else [])
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out: List[str] = []
            for e in node.elts:
                out.extend(self.axis_literals(e))
            return out
        if isinstance(node, ast.Name):
            v = self.resolve_name(node.id)
            return [v] if v is not None else []
        return []


# ------------------------------------------------------ collective-axis

#: named-axis collectives -> positional index of the axis-name operand
#: (0.9 names; pcast/pbroadcast are the vma-cast pair the jaxcompat
#: shim grafts onto 0.4.x — the AST spelling is identical either way)
_COLLECTIVE_AXIS_POS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1, "pshuffle": 1,
    "pcast": 1, "pbroadcast": 1, "axis_index": 0, "axis_size": 0,
}
#: keyword spellings of the axis operand on those calls
_COLLECTIVE_AXIS_KW = ("axis_name", "axes")


class _CollectiveAxisVisitor(_FuncScoper):
    def __init__(self, sf: SourceFile, axes: Dict[str, Optional[int]],
                 lax_aliases: Dict[str, str], findings: List[Finding]):
        super().__init__()
        self.sf = sf
        self.axes = axes
        self.lax_aliases = lax_aliases
        self.scopes = _AxisScopes(sf.tree)
        self.findings = findings

    def enter_function(self, node, qualname):
        self.scopes.push(node)

    def exit_function(self, node):
        self.scopes.pop()

    def _collective_name(self, func) -> Optional[str]:
        """The CANONICAL collective name when this callee is one:
        jax.lax.psum / lax.psum / a from-import or module-level alias
        of one (``from jax.lax import psum as ps`` resolves to
        ``psum``)."""
        if isinstance(func, ast.Attribute) \
                and func.attr in _COLLECTIVE_AXIS_POS:
            chain = _jax_chain(func)
            if "lax" in chain[:-1] or chain[0] in ("jax", "collective"):
                return func.attr
        if isinstance(func, ast.Name):
            return self.lax_aliases.get(func.id)
        return None

    def _check_axis_expr(self, node, expr, what: str):
        for axis in self.scopes.axis_literals(expr):
            if axis not in self.axes:
                registered = ", ".join(sorted(self.axes)) or "<none>"
                self.findings.append(self.sf.finding(
                    "collective-axis", node,
                    f"{what} names mesh axis {axis!r}, which is not "
                    f"registered in parallel.topology.KNOWN_AXES "
                    f"({registered}) — a typo'd or out-of-scope axis "
                    f"only fails at trace time on a multichip mesh"))

    def visit_Call(self, node):
        name = self._collective_name(node.func)
        if name is not None:
            pos = _COLLECTIVE_AXIS_POS[name]
            expr = node.args[pos] if pos < len(node.args) else None
            if expr is None:
                for kw in node.keywords:
                    if kw.arg in _COLLECTIVE_AXIS_KW:
                        expr = kw.value
                        break
            if expr is not None:
                self._check_axis_expr(node, expr, f"lax.{name}")
        else:
            # currying sites: axis_name= on any call (partial(local,
            # axis_name=ax), shard_map(..., axis_names={...}))
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    self._check_axis_expr(node, kw.value,
                                          f"{kw.arg}= keyword")
        self.generic_visit(node)


def _lax_collective_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> CANONICAL collective name for ``from jax.lax
    import psum [as ps]`` bindings and module-level ``psum =
    jax.lax.psum`` re-exports (parallel/collective.py's in-jit
    primitives)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax.lax":
            for a in node.names:
                if a.name in _COLLECTIVE_AXIS_POS:
                    out[a.asname or a.name] = a.name
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr in _COLLECTIVE_AXIS_POS \
                and "lax" in _jax_chain(node.value)[:-1]:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.attr
    return out


def check_collective_axis(sf: SourceFile,
                          axes: Dict[str, Optional[int]]
                          ) -> List[Finding]:
    findings: List[Finding] = []
    _CollectiveAxisVisitor(sf, axes, _lax_collective_aliases(sf.tree),
                           findings).visit(sf.tree)
    return findings


# ---------------------------------------------------------- pspec-axis

def _pspec_aliases(tree: ast.Module) -> Set[str]:
    out = {"PartitionSpec"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "PartitionSpec":
                    out.add(a.asname or a.name)
    return out


class _PspecVisitor(_FuncScoper):
    def __init__(self, sf: SourceFile, axes: Dict[str, Optional[int]],
                 p_names: Set[str], findings: List[Finding]):
        super().__init__()
        self.sf = sf
        self.axes = axes
        self.p_names = p_names
        self.scopes = _AxisScopes(sf.tree)
        self.findings = findings

    def enter_function(self, node, qualname):
        self.scopes.push(node)

    def exit_function(self, node):
        self.scopes.pop()

    def _is_pspec(self, func) -> bool:
        if isinstance(func, ast.Name):
            return func.id in self.p_names
        return isinstance(func, ast.Attribute) \
            and func.attr == "PartitionSpec"

    def _dim_axes(self, expr) -> List[str]:
        return self.scopes.axis_literals(expr)

    def visit_Call(self, node):
        if self._is_pspec(node.func):
            for arg in node.args:
                for axis in self._dim_axes(arg):
                    if axis not in self.axes:
                        registered = ", ".join(sorted(self.axes)) \
                            or "<none>"
                        self.findings.append(self.sf.finding(
                            "pspec-axis", node,
                            f"PartitionSpec references mesh axis "
                            f"{axis!r}, which is not registered in "
                            f"parallel.topology.KNOWN_AXES "
                            f"({registered})"))
        else:
            self._check_divisibility(node)
        self.generic_visit(node)

    def _check_divisibility(self, node):
        """jax.ShapeDtypeStruct((4, 6), ..., sharding=NamedSharding(
        mesh, P("dp", None))) — the statically-knowable case: each
        sharded dim must divide by the axis's validated degree."""
        f = node.func
        name = (f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None)
        if name != "ShapeDtypeStruct" or not node.args:
            return
        shape = node.args[0]
        if not isinstance(shape, (ast.Tuple, ast.List)):
            return
        dims = [e.value if isinstance(e, ast.Constant)
                and isinstance(e.value, int) else None
                for e in shape.elts]
        spec = None
        for kw in node.keywords:
            if kw.arg == "sharding":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Call) \
                            and self._is_pspec(sub.func):
                        spec = sub
                        break
        if spec is None:
            return
        for i, arg in enumerate(spec.args):
            if i >= len(dims) or dims[i] is None:
                continue
            degree = 1
            for axis in self._dim_axes(arg):
                d = self.axes.get(axis)
                degree *= d if d else 1
            if degree > 1 and dims[i] % degree:
                self.findings.append(self.sf.finding(
                    "pspec-axis", spec,
                    f"dim {i} of size {dims[i]} is sharded over axes "
                    f"of validated degree {degree} "
                    f"(parallel.topology.KNOWN_AXES) but is not "
                    f"divisible by it — this spec fails at lowering "
                    f"time on the real mesh"))


def check_pspec_axis(sf: SourceFile, axes: Dict[str, Optional[int]]
                     ) -> List[Finding]:
    findings: List[Finding] = []
    _PspecVisitor(sf, axes, _pspec_aliases(sf.tree),
                  findings).visit(sf.tree)
    return findings


# ------------------------------------------------------------ donation

#: .at[...].<mutator> suffixes — the RMW half of the donation contract
_AT_MUTATORS = {"set", "add", "subtract", "multiply", "divide", "power",
                "min", "max", "apply"}
_DUS_NAMES = {"dynamic_update_slice", "dynamic_update_slice_in_dim",
              "dynamic_update_index_in_dim"}
#: the sanctioned conditional-donation helper (inference.
#: carry_donate_argnums): the rule reads argnums through a call to any
#: name with this suffix
_DONATION_HELPER_SUFFIX = "donate_argnums"


class _FnEntry:
    """One function with its lexical scope links — the donation rule's
    unit of analysis."""

    __slots__ = ("sf", "module", "qualname", "node", "parent", "locals",
                 "params", "vararg", "nparams", "rmw", "taint")

    def __init__(self, sf, module, qualname, node, parent):
        self.sf = sf
        self.module = module
        self.qualname = qualname
        self.node = node
        self.parent = parent            # enclosing _FnEntry or None
        self.locals: Dict[str, "_FnEntry"] = {}
        a = node.args
        pos = a.posonlyargs + a.args
        self.params = {p.arg: i for i, p in enumerate(pos)}
        self.nparams = len(pos)
        self.vararg = a.vararg.arg if a.vararg else None
        #: (param position, carry component) pairs RMW'd into an
        #: output; component None = the whole argument, an int = the
        #: i-th element of a tuple-valued argument (so a scan carry
        #: whose POOL component is RMW'd does not taint its token and
        #: position components)
        self.rmw: Set[tuple] = set()
        #: _fn_taint cache — taint depends only on this function's own
        #: params/assigns, never on other entries' facts, so it is
        #: invariant across fixpoint sweeps
        self.taint: Optional[Dict[str, Set[tuple]]] = None

    def rmw_argnums(self) -> Set[int]:
        return {p for p, _ in self.rmw}

    def param_label(self, pos: int) -> str:
        for name, i in self.params.items():
            if i == pos:
                return name
        if self.vararg is not None and pos >= self.nparams:
            return f"*{self.vararg}[{pos - self.nparams}]"
        return f"argnum {pos}"


def _walk_shallow(node):
    """Walk a function body without descending into nested function /
    class definitions (their params shadow; they are entries of their
    own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


class _DonationIndex:
    """All functions in the package, with lexical-scope and
    cross-module (from-import / module-alias) name resolution."""

    def __init__(self, files: Dict[str, SourceFile], graph):
        self.entries: List[_FnEntry] = []
        self.by_module: Dict[str, Dict[str, List[_FnEntry]]] = {}
        self.by_node: Dict[int, _FnEntry] = {}
        self.graph = graph
        for path, sf in files.items():
            module = _module_name(path)
            self.by_module.setdefault(module, {})
            self._collect(sf, module, sf.tree, None, [])

    def _collect(self, sf, module, node, parent, qual):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                q = ".".join(qual + [child.name])
                e = _FnEntry(sf, module, q, child, parent)
                self.entries.append(e)
                self.by_node[id(child)] = e
                self.by_module[module].setdefault(child.name,
                                                  []).append(e)
                if parent is not None:
                    parent.locals.setdefault(child.name, e)
                self._collect(sf, module, child, e, qual + [child.name])
            elif isinstance(child, ast.ClassDef):
                self._collect(sf, module, child, parent,
                              qual + [child.name])
            else:
                self._collect(sf, module, child, parent, qual)

    def resolve(self, entry: _FnEntry, func) -> List[_FnEntry]:
        """Callee candidates for a Call's func expression: nearest
        lexical scope, then module, then explicit imports (the same
        name discipline as analysis/callgraph.py)."""
        if isinstance(func, ast.Name):
            name = func.id
            e = entry
            while e is not None:
                if name in e.locals:
                    return [e.locals[name]]
                e = e.parent
            hits = self.by_module.get(entry.module, {}).get(name)
            if hits:
                return hits
            src = self.graph.from_imports.get(entry.module,
                                              {}).get(name)
            if src is not None:
                return self.by_module.get(src[0], {}).get(src[1], [])
            return []
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name):
            base = func.value.id
            if base in ("self", "cls"):
                return self.by_module.get(entry.module,
                                          {}).get(func.attr, [])
            mod = self.graph.module_imports.get(entry.module,
                                                {}).get(base)
            if mod is not None:
                return self.by_module.get(mod, {}).get(func.attr, [])
        return []


def _root_name(node) -> Optional[ast.AST]:
    """Peel subscripts down to the Name a buffer expression roots at
    (``carry[1]`` -> carry); attribute reads are NOT peeled (``x.T``
    is a view of a different object in the taint sense we need)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return node if isinstance(node, ast.Name) else None


def _taint_positions(node, taint: Dict[str, Set[tuple]],
                     entry: _FnEntry) -> Set[tuple]:
    """(param position, component) pairs this expression's BUFFER may
    alias: a tainted Name, a subscript of one (``carry[1]``), or — for
    the vararg — a constant subscript resolving to ``nparams + i``."""
    if isinstance(node, ast.IfExp):
        return _taint_positions(node.body, taint, entry) \
            | _taint_positions(node.orelse, taint, entry)
    if isinstance(node, ast.Subscript) \
            and isinstance(node.value, ast.Name) \
            and entry.vararg is not None \
            and node.value.id == entry.vararg:
        idx = node.slice
        if isinstance(idx, ast.Constant) and isinstance(idx.value, int):
            return {(entry.nparams + idx.value, None)}
        return set()
    root = _root_name(node)
    if root is not None:
        return taint.get(root.id, set())
    return set()


def _fn_taint(entry: _FnEntry) -> Dict[str, Set[tuple]]:
    """name -> (param position, component) pairs whose buffer the
    local may alias. Buffer-preserving flows only: plain rebinds,
    tuple unpacks and subscripts — ``y = kv + 1`` is a NEW buffer and
    must not taint. A tuple unpack from a whole-argument name tags
    each target with its component index (``tok, kv, keys = carry``:
    kv is component 1 of carry's buffer tree — an RMW on kv must not
    implicate tok)."""
    taint: Dict[str, Set[tuple]] = {n: {(p, None)}
                                    for n, p in entry.params.items()}
    for _ in range(2):
        for sub in _walk_shallow(entry.node):
            if not isinstance(sub, ast.Assign):
                continue
            pairs = _taint_positions(sub.value, taint, entry)
            for t in sub.targets:
                if isinstance(t, ast.Name) and pairs:
                    taint.setdefault(t.id, set()).update(pairs)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    if isinstance(sub.value, (ast.Tuple, ast.List)) \
                            and len(sub.value.elts) == len(t.elts):
                        # element-wise: (a, b) = (x, y)
                        for te, ve in zip(t.elts, sub.value.elts):
                            p = _taint_positions(ve, taint, entry)
                            if isinstance(te, ast.Name) and p:
                                taint.setdefault(te.id,
                                                 set()).update(p)
                    elif pairs:
                        # `tok, kv, keys = carry`: component-tagged
                        for i, te in enumerate(t.elts):
                            if not isinstance(te, ast.Name):
                                continue
                            tagged = {(p, i if c is None else c)
                                      for p, c in pairs}
                            taint.setdefault(te.id,
                                             set()).update(tagged)
    return taint


#: lax control-flow combinators: (callee positional index of the body
#: fn, positional index of the carry operand, carry's param position
#: in the body fn)
_CARRY_COMBINATORS = {"scan": (0, 1, 0), "while_loop": (1, 2, 0),
                      "fori_loop": (2, 3, 1)}


def _peel_partial(func_expr):
    """(callable expr, n leading curried positional args) — peeling
    functools.partial(f, a, b) so body-param indexing shifts. The
    partial predicate is SHARED with the callgraph's entry marking
    (analysis/callgraph.py) so the two passes never disagree on what
    counts as a curried callable."""
    from paddle_tpu.analysis.callgraph import _is_partial
    if isinstance(func_expr, ast.Call) and _is_partial(func_expr.func) \
            and func_expr.args:
        return func_expr.args[0], len(func_expr.args) - 1
    return func_expr, 0


def _rmw_pass(index: _DonationIndex) -> bool:
    """One fixpoint sweep: grow each function's RMW'd-param set from
    direct RMW sites, resolvable callees' facts, and control-flow
    carries. Returns whether anything changed."""
    changed = False
    for entry in index.entries:
        if entry.taint is None:
            entry.taint = _fn_taint(entry)
        taint = entry.taint
        found: Set[tuple] = set()
        for sub in _walk_shallow(entry.node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            # x.at[...].set(...) — receiver buffer is RMW'd
            if isinstance(f, ast.Attribute) and f.attr in _AT_MUTATORS \
                    and isinstance(f.value, ast.Subscript) \
                    and isinstance(f.value.value, ast.Attribute) \
                    and f.value.value.attr == "at":
                found |= _taint_positions(f.value.value.value, taint,
                                          entry)
                continue
            name = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else None)
            if name in _DUS_NAMES and sub.args:
                found |= _taint_positions(sub.args[0], taint, entry)
                continue
            if name in _CARRY_COMBINATORS and len(sub.args) \
                    > _CARRY_COMBINATORS[name][1]:
                body_i, carry_i, carry_pos = _CARRY_COMBINATORS[name]
                body_expr, offset = _peel_partial(sub.args[body_i])
                init = sub.args[carry_i]
                for cand in index.resolve(entry, body_expr):
                    for pos, comp in cand.rmw:
                        if pos != carry_pos + offset:
                            continue
                        if comp is not None and isinstance(
                                init, (ast.Tuple, ast.List)) \
                                and comp < len(init.elts):
                            # only the RMW'd carry COMPONENT taints
                            found |= _taint_positions(init.elts[comp],
                                                      taint, entry)
                        else:
                            elts = (init.elts if isinstance(
                                init, (ast.Tuple, ast.List))
                                else [init])
                            for e in elts:
                                found |= _taint_positions(e, taint,
                                                          entry)
                continue
            # ordinary call into a function with known RMW facts
            for cand in index.resolve(entry, f):
                if not cand.rmw:
                    continue
                # bound-method calls (self.scatter(pool, i)) consume
                # the callee's param 0 as the receiver: caller arg j
                # binds callee param j+1
                recv = 1 if (isinstance(f, ast.Attribute)
                             and isinstance(f.value, ast.Name)
                             and f.value.id in ("self", "cls")
                             and (cand.params.get("self") == 0
                                  or cand.params.get("cls") == 0)) \
                    else 0
                for pos in cand.rmw_argnums():
                    ai = pos - recv
                    if 0 <= ai < len(sub.args):
                        found |= _taint_positions(sub.args[ai], taint,
                                                  entry)
                rmw_names = {n for n, i in cand.params.items()
                             if i in cand.rmw_argnums()}
                for kw in sub.keywords:
                    if kw.arg in rmw_names:
                        found |= _taint_positions(kw.value, taint,
                                                  entry)
        if not found <= entry.rmw:
            entry.rmw |= found
            changed = True
    return changed


def _donated_argnums(jit_call: ast.Call) -> Optional[Set[int]]:
    """The donated set a jit site declares: a tuple/int literal, an
    ``(...) if cond else ()`` conditional (counted as donated — the
    enabled branch is the contract), or a call to the sanctioned
    ``*_donate_argnums`` helper. NO donate_argnums keyword returns
    ``set()`` (nothing donated — the rule's main flagging case); an
    UNRESOLVABLE expression returns None and the rule skips the site
    rather than guessing. A ``donate_argnames=`` spelling also returns
    None: this rule reasons by position, and a by-name donation must
    not be flagged as undonated."""
    expr = None
    if any(kw.arg == "donate_argnames" for kw in jit_call.keywords):
        return None
    for kw in jit_call.keywords:
        if kw.arg == "donate_argnums":
            expr = kw.value
            break
    if expr is None:
        return set()

    def parse(e) -> Optional[Set[int]]:
        if isinstance(e, ast.Constant):
            return {e.value} if isinstance(e.value, int) else None
        if isinstance(e, (ast.Tuple, ast.List)):
            out: Set[int] = set()
            for el in e.elts:
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, int):
                    out.add(el.value)
                else:
                    return None
            return out
        if isinstance(e, ast.IfExp):
            a, b = parse(e.body), parse(e.orelse)
            if a is None and b is None:
                return None
            return (a or set()) | (b or set())
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
            a, b = parse(e.left), parse(e.right)
            if a is None or b is None:
                return None
            return a | b
        if isinstance(e, ast.Call):
            f = e.func
            name = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else "")
            if name.endswith(_DONATION_HELPER_SUFFIX):
                out = set()
                for el in e.args:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, int):
                        out.add(el.value)
                    else:
                        return None
                return out
        return None

    return parse(expr)


def _is_jit_callee(func) -> bool:
    if isinstance(func, ast.Name):
        return func.id in ("jit", "pjit")
    return isinstance(func, ast.Attribute) and func.attr in ("jit",
                                                             "pjit")


class _DonationVisitor(_FuncScoper):
    """Per-file pass over jit sites: undonated-RMW findings plus the
    donated-then-reused caller hazard."""

    def __init__(self, sf: SourceFile, index: _DonationIndex,
                 module: str, findings: List[Finding]):
        super().__init__()
        self.sf = sf
        self.index = index
        self.module = module
        self.findings = findings
        # per function frame: local name -> (donated set, jit Call);
        # plus (call node, donated arg Name, position) dispatch records
        self.frames: List[Dict] = [{"jitted": {}, "dispatches": []}]
        self.entry_stack: List[Optional[_FnEntry]] = [None]

    def enter_function(self, node, qualname):
        self.frames.append({"jitted": {}, "dispatches": []})
        entry = self.index.by_node.get(id(node))
        self.entry_stack.append(entry)
        if entry is not None:
            self._check_decorators(node, entry)

    def _check_decorators(self, node, entry):
        """@jax.jit / @functools.partial(jax.jit, donate_argnums=...)
        — the decorator-form jit site."""
        for dec in node.decorator_list:
            call = None
            if isinstance(dec, ast.Call) and _is_jit_callee(dec.func):
                call = dec
            elif isinstance(dec, ast.Call) \
                    and any(_is_jit_callee(a) for a in dec.args):
                call = dec                  # partial(jax.jit, ...)
            elif _is_jit_callee(dec):
                for pos in sorted(entry.rmw_argnums()):
                    self.findings.append(self._rmw_finding(dec, entry,
                                                           pos))
                return
            if call is None:
                continue
            donated = _donated_argnums(call)
            if donated is None:
                return
            for pos in sorted(entry.rmw_argnums() - donated):
                self.findings.append(self._rmw_finding(call, entry,
                                                       pos))
            return

    def _rmw_finding(self, node, cand: _FnEntry, pos: int) -> Finding:
        return self.sf.finding(
            "donation", node,
            f"{cand.qualname}() RMWs its {cand.param_label(pos)} "
            f"(argnum {pos}) into an output, but this jit site does "
            f"not donate it — every dispatch copies the buffer (the "
            f"BENCH_r06 carry-copy class); add it to donate_argnums "
            f"or annotate why the copy is intended")

    def exit_function(self, node):
        self._flush_frame(node)
        self.entry_stack.pop()

    def exit_module(self):
        self._flush_frame(self.sf.tree)

    def _flush_frame(self, scope_node):
        frame = self.frames.pop()
        for call, arg_name, pos in frame["dispatches"]:
            self._check_reuse(scope_node, call, arg_name, pos)

    def _check_reuse(self, scope_node, call, arg_name, pos):
        """A donated argument read again after the dispatch line (with
        no intervening rebind) is a use-after-free on any backend that
        honors donation."""
        end = getattr(call, "end_lineno", call.lineno)
        stores = []
        loads = []
        for sub in _walk_shallow(scope_node):
            if not isinstance(sub, ast.Name) or sub.id != arg_name:
                continue
            # stores ON the dispatch line count as rebinds — the
            # canonical `kv = j(kv, xs)` spelling rebinds the name to
            # the program output in the dispatch statement itself;
            # loads on that line are the dispatch arguments, not reuse
            if isinstance(sub.ctx, ast.Store) \
                    and sub.lineno >= call.lineno:
                stores.append(sub.lineno)
            elif isinstance(sub.ctx, ast.Load) and sub.lineno > end:
                loads.append(sub.lineno)
        for ln in sorted(loads):
            # strictly-earlier stores only: `kv = kv + 1` READS the
            # donated buffer before its own same-line store
            if any(s < ln for s in stores):
                break           # rebound before this read: fresh value
            self.findings.append(Finding(
                "donation", self.sf.path, ln, 0,
                f"{arg_name!r} is donated to the dispatch on line "
                f"{call.lineno} and read again here — donated buffers "
                f"are deleted; this is a use-after-free wherever "
                f"donation is honored", self.sf.line_text(ln)))
            break               # one finding per dispatch

    def visit_Call(self, node):
        if _is_jit_callee(node.func) and node.args:
            donated = _donated_argnums(node)
            target_expr = node.args[0]
            entry = self.entry_stack[-1]
            candidates = []
            if entry is not None:
                candidates = self.index.resolve(entry, target_expr)
            elif isinstance(target_expr, ast.Name):
                candidates = self.index.by_module.get(
                    self.module, {}).get(target_expr.id, [])
            if donated is not None and candidates:
                cand = candidates[0]
                for pos in sorted(cand.rmw_argnums() - donated):
                    self.findings.append(self._rmw_finding(node, cand,
                                                           pos))
        else:
            # dispatch through a jitted local: record donated-arg names
            f = node.func
            frame = self.frames[-1]
            rec = None
            if isinstance(f, ast.Name):
                # nearest enclosing frame holding the handle — a
                # module-level `j = jax.jit(...)` dispatched inside a
                # function is still a donation site
                for fr in reversed(self.frames):
                    if f.id in fr["jitted"]:
                        rec = fr["jitted"][f.id]
                        break
            elif isinstance(f, ast.Call) and _is_jit_callee(f.func):
                d = _donated_argnums(f)
                rec = d if d else None
            if rec:
                for pos in rec:
                    if pos < len(node.args) \
                            and isinstance(node.args[pos], ast.Name) \
                            and not any(isinstance(a, ast.Starred)
                                        for a in node.args[:pos]):
                        frame["dispatches"].append(
                            (node, node.args[pos].id, pos))
        self.generic_visit(node)

    def visit_Assign(self, node):
        # `jitted = jax.jit(impl, donate_argnums=...)` — remember the
        # local handle's donated set for dispatch-site reuse checks
        if isinstance(node.value, ast.Call) \
                and _is_jit_callee(node.value.func):
            donated = _donated_argnums(node.value)
            if donated:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.frames[-1]["jitted"][t.id] = donated
        self.generic_visit(node)


def check_donation(files: Dict[str, SourceFile], graph
                   ) -> List[Finding]:
    index = _DonationIndex(files, graph)
    for _ in range(8):              # cross-module fixpoint
        if not _rmw_pass(index):
            break
    findings: List[Finding] = []
    for path, sf in files.items():
        v = _DonationVisitor(sf, index, _module_name(path), findings)
        v.visit(sf.tree)
        v.exit_module()
    return findings


# -------------------------------------------------------------- driver

def _module_name(path: str) -> str:
    module = os.path.splitext(path.replace(os.sep, "/"))[0].replace(
        "/", ".")
    if module.endswith(".__init__"):
        module = module[: -len(".__init__")]
    return module


ALL_RULES = ("host-sync", "traced-branch", "default-dtype",
             "metric-drift", "span-drift", "fault-site",
             "snapshot-coverage", "journal-coverage", "rng-stream",
             "collective-axis", "pspec-axis", "donation")


def run_rules(files: Dict[str, SourceFile], graph, docs_text: str,
              fault_sites: Set[str],
              rules=ALL_RULES,
              known_axes: Optional[Dict[str, Optional[int]]] = None
              ) -> List[Finding]:
    findings: List[Finding] = []
    axes = known_axes or {}
    per_file = {"host-sync": lambda sf: check_host_sync(sf, graph),
                "traced-branch": lambda sf: check_traced_branch(sf, graph),
                "default-dtype": check_default_dtype,
                "fault-site": lambda sf: check_fault_site(sf, fault_sites),
                "snapshot-coverage": check_snapshot_coverage,
                "collective-axis":
                    lambda sf: check_collective_axis(sf, axes),
                "pspec-axis": lambda sf: check_pspec_axis(sf, axes)}
    aggregate = {"journal-coverage": check_journal_coverage,
                 "rng-stream": check_rng_stream,
                 "donation": lambda fs: check_donation(fs, graph)}
    docs_checks = {"metric-drift": check_metric_drift,
                   "span-drift": check_span_drift}
    for rule in rules:
        if rule in docs_checks:
            sources = {p: sf.source for p, sf in files.items()}
            findings.extend(docs_checks[rule](
                sources, docs_text,
                lambda p, ln: files[p].line_text(ln)))
            continue
        if rule in aggregate:
            findings.extend(aggregate[rule](files))
            continue
        fn = per_file[rule]
        for sf in files.values():
            findings.extend(fn(sf))
    findings.sort(key=Finding.sort_key)
    return findings
