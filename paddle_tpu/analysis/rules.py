"""The tpu-lint rule set — repo-specific hot-path invariants as checks.

Every rule yields :class:`Finding`s; the driver (analysis/lint.py)
applies inline suppressions (``# tpu-lint: allow(<rule>)``) and the
checked-in baseline on top, so a rule is free to be *conservative*
(flag everything that is shaped like a violation) and let intentional
sites be annotated where they live.

Rule catalog (docs/ANALYSIS.md has the workflow):

``host-sync``
    Implicit host synchronization: ``.item()``, ``np.asarray`` /
    ``np.array`` / ``np.ascontiguousarray`` on non-literal arguments
    (a device array operand forces a D2H pull), ``jax.device_get``,
    ``block_until_ready``, and — inside jit-reachable functions only —
    ``float()/int()/bool()`` on array-shaped values (a concretization
    sync under trace). One stray site on the decode hot path regresses
    dispatch latency silently; every intentional site must say why.

``traced-branch``
    Python ``if``/``while``/``assert``/ternary on a value produced by
    a ``jnp``/``lax`` computation inside a function reachable from a
    ``jax.jit``/``pjit`` entry point (analysis/callgraph.py) — under
    trace this is a ConcretizationError at best, a silent
    recompile-per-value at worst. Static extractions (``.shape``,
    ``.ndim``, ``.dtype``, ``len()``, ``is None``) are exempt.

``default-dtype``
    Kernel files (``ops/``, ``inference/``, ``serving/``): numpy array
    creation with the implicit float64/int64 default dtype, and any
    explicit ``float64`` — a float64 operand silently doubles memory
    traffic and detunes TPU-shaped kernels.

``metric-drift``
    Every ``counter/gauge/histogram/sketch("serving.|resilience.|
    decode.*")`` literal in the package must appear in
    docs/OBSERVABILITY.md (the PR 7 drift grep, promoted to a rule —
    tests/test_slo.py delegates here).

``fault-site``
    ``maybe_fire(...)`` / ``Fault(...)`` site literals must be
    registered in ``resilience.faults.KNOWN_SITES`` — an unregistered
    site is a hook the fault-injection docs and chaos tooling cannot
    see.
"""

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set

__all__ = ["Finding", "ALL_RULES", "KERNEL_DIRS", "collect_metric_names",
           "known_fault_sites", "run_rules"]

KERNEL_DIRS = ("paddle_tpu/ops", "paddle_tpu/inference",
               "paddle_tpu/serving")

_NUMPY_CREATORS = {"zeros", "ones", "empty", "full", "arange",
                   "linspace", "eye", "identity"}
_DTYPE_NAMES = {"float32", "float16", "bfloat16", "float64", "int8",
                "int16", "int32", "int64", "uint8", "uint16", "uint32",
                "uint64", "bool_", "complex64", "intp", "float0"}
#: jnp/lax attribute calls that return static METADATA, not traced data
_STATIC_MODULE_CALLS = {"dtype", "issubdtype", "result_type",
                        "promote_types", "iinfo", "finfo", "shape",
                        "ndim", "size"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize",
                 "weak_type", "sharding", "nbytes"}
_TRACED_ROOTS = {"jnp", "lax"}
_TRACED_JAX_SUBMODULES = {"nn", "random", "numpy", "lax", "scipy"}

_METRIC_CALL = re.compile(
    r'(?:counter|gauge|histogram|sketch)\(\s*'
    r'"((?:serving|resilience|decode)\.[a-z0-9_.]+)"')


class Finding:
    """One lint violation. ``code`` is the stripped source line — the
    baseline matches on (rule, path, code), so findings survive
    unrelated edits that only shift line numbers."""

    __slots__ = ("rule", "path", "line", "col", "message", "code")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, code: str = ""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.code = code

    def key(self):
        return (self.rule, self.path, self.code)

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_json(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "code": self.code}

    def __repr__(self):
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}")


class SourceFile:
    __slots__ = ("path", "source", "lines", "tree")

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node, message: str) -> Finding:
        return Finding(rule, self.path, node.lineno, node.col_offset,
                       message, self.line_text(node.lineno))


# --------------------------------------------------------------- helpers

def _numpy_aliases(tree: ast.Module) -> Set[str]:
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    names.add(a.asname or "numpy")
        elif isinstance(node, ast.ImportFrom) and node.module == "numpy":
            names.add("__from_numpy__")
    return names


def _attr_root(node) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_host_literal(node) -> bool:
    """Arguments that are host data by construction: literals,
    comprehensions, and pure-numpy expressions."""
    if isinstance(node, (ast.Constant, ast.List, ast.Tuple, ast.Dict,
                         ast.ListComp, ast.GeneratorExp, ast.DictComp,
                         ast.SetComp)):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_host_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_host_literal(node.left) and _is_host_literal(node.right)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        # list(...)/sorted(...) results are host objects by construction
        return node.func.id in ("list", "tuple", "sorted", "range")
    return False


def _looks_like_dtype(node) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in _DTYPE_NAMES or node.attr == "dtype"
    if isinstance(node, ast.Name):
        return node.id in _DTYPE_NAMES or "dtype" in node.id.lower()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _DTYPE_NAMES
    if isinstance(node, ast.Call):
        # np.dtype(...), jnp.dtype(...), x.astype's operand etc.
        return (isinstance(node.func, ast.Attribute)
                and node.func.attr == "dtype")
    return False


def _static_extraction(node) -> bool:
    """Expressions whose VALUE is static under trace even when the
    operand is traced: shape/dtype attributes, len(), isinstance(),
    identity comparisons."""
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.Subscript):
        return _static_extraction(node.value)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("len", "isinstance", "hasattr", "getattr",
                                "type")
    return False


def _tainted(node, traced: Set[str]) -> bool:
    """Does this expression's value depend on traced array DATA (as
    opposed to static metadata)?"""
    if node is None or isinstance(node, ast.Constant):
        return False
    if _static_extraction(node):
        return False
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute):
        return _tainted(node.value, traced)
    if isinstance(node, ast.Subscript):
        return _tainted(node.value, traced)
    if isinstance(node, ast.Call):
        root = _attr_root(node.func)
        if root in _TRACED_ROOTS:
            return (not isinstance(node.func, ast.Attribute)
                    or node.func.attr not in _STATIC_MODULE_CALLS)
        if root == "jax" and isinstance(node.func, ast.Attribute):
            # jax.nn.softmax(x) / jax.random.fold_in(...) return traced
            # data; jax.default_backend() and friends do not
            chain = _jax_chain(node.func)
            if len(chain) >= 2 and chain[1] in _TRACED_JAX_SUBMODULES:
                return True
        args = list(node.args) + [kw.value for kw in node.keywords]
        if isinstance(node.func, ast.Attribute) \
                and _tainted(node.func.value, traced):
            return True         # x.astype(...), x.sum() on tainted x
        return any(_tainted(a, traced) for a in args)
    if isinstance(node, ast.BinOp):
        return _tainted(node.left, traced) or _tainted(node.right, traced)
    if isinstance(node, ast.UnaryOp):
        return _tainted(node.operand, traced)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in node.ops):
            return False        # identity / membership: host semantics
        return _tainted(node.left, traced) \
            or any(_tainted(c, traced) for c in node.comparators)
    if isinstance(node, ast.BoolOp):
        return any(_tainted(v, traced) for v in node.values)
    if isinstance(node, ast.IfExp):
        return _tainted(node.body, traced) or _tainted(node.orelse, traced)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_tainted(e, traced) for e in node.elts)
    return False


def _jax_chain(node) -> List[str]:
    chain = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        chain.append(node.id)
    return list(reversed(chain))


class _FuncScoper(ast.NodeVisitor):
    """Shared walk that attributes nodes to their enclosing function's
    qualname (matching analysis/callgraph.py) before dispatching to a
    per-rule ``handle(node, qualname)``."""

    def __init__(self):
        self.stack: List[str] = []

    def _visit_func(self, node):
        self.stack.append(node.name)
        self.enter_function(node, ".".join(self.stack))
        self.generic_visit(node)
        self.exit_function(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def enter_function(self, node, qualname):   # pragma: no cover
        pass

    def exit_function(self, node):              # pragma: no cover
        pass


# ----------------------------------------------------------- host-sync

class _HostSyncVisitor(_FuncScoper):
    def __init__(self, sf: SourceFile, np_aliases: Set[str],
                 is_traced_fn, findings: List[Finding]):
        super().__init__()
        self.sf = sf
        self.np = np_aliases
        self.is_traced_fn = is_traced_fn
        self.findings = findings

    def visit_Call(self, node):
        f = node.func
        sf = self.sf
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args:
                self.findings.append(sf.finding(
                    "host-sync", node,
                    ".item() forces a device sync + D2H scalar pull"))
            elif f.attr == "block_until_ready":
                self.findings.append(sf.finding(
                    "host-sync", node,
                    "block_until_ready blocks the host on device work"))
            elif f.attr == "device_get" and _attr_root(f) == "jax":
                self.findings.append(sf.finding(
                    "host-sync", node,
                    "jax.device_get is an explicit D2H transfer"))
            elif (f.attr in ("asarray", "array", "ascontiguousarray")
                  and isinstance(f.value, ast.Name)
                  and f.value.id in self.np and node.args
                  and not _is_host_literal(node.args[0])
                  and not self._numpy_arg(node.args[0])):
                self.findings.append(sf.finding(
                    "host-sync", node,
                    f"np.{f.attr} on a possibly-device value syncs and "
                    f"copies to host"))
        elif isinstance(f, ast.Name):
            if f.id == "block_until_ready":
                self.findings.append(sf.finding(
                    "host-sync", node,
                    "block_until_ready blocks the host on device work"))
            elif f.id in ("float", "int", "bool") and len(node.args) == 1 \
                    and self._in_traced_function() \
                    and self._concretizes(node.args[0]):
                self.findings.append(sf.finding(
                    "host-sync", node,
                    f"{f.id}() on an array value in jit-reachable code "
                    f"is a concretization sync"))
        self.generic_visit(node)

    def _numpy_arg(self, node) -> bool:
        """np.asarray(np.stack(...)) — already host, not a sync."""
        return (isinstance(node, ast.Call)
                and _attr_root(node.func) in self.np)

    def _in_traced_function(self) -> bool:
        return bool(self.stack) and self.is_traced_fn(
            ".".join(self.stack))

    def _concretizes(self, arg) -> bool:
        """float(x)-style casts that force a device value concrete:
        calls and subscripts of non-static expressions. Plain names and
        static metadata (shape/len/...) stay un-flagged — config casts
        are the common benign case."""
        if _static_extraction(arg) or isinstance(arg, (ast.Constant,
                                                       ast.Name,
                                                       ast.Attribute)):
            # plain names and attribute reads are the benign config-cast
            # case; only value-producing expressions (calls, subscripts)
            # can force a device array concrete
            return False
        if isinstance(arg, (ast.Call, ast.Subscript)):
            return not _static_extraction(arg)
        if isinstance(arg, ast.BinOp):
            return self._concretizes(arg.left) \
                or self._concretizes(arg.right)
        if isinstance(arg, ast.UnaryOp):
            return self._concretizes(arg.operand)
        return False


def check_host_sync(sf: SourceFile, graph) -> List[Finding]:
    module = _module_name(sf.path)
    findings: List[Finding] = []
    v = _HostSyncVisitor(
        sf, _numpy_aliases(sf.tree),
        lambda qual: graph.is_traced(module, qual), findings)
    v.visit(sf.tree)
    return findings


# -------------------------------------------------------- traced-branch

class _TracedBranchVisitor(_FuncScoper):
    def __init__(self, sf: SourceFile, is_traced_fn,
                 findings: List[Finding]):
        super().__init__()
        self.sf = sf
        self.is_traced_fn = is_traced_fn
        self.findings = findings
        self.traced_vars: List[Set[str]] = []

    def enter_function(self, node, qualname):
        # locals assigned from jnp/lax computations are traced values;
        # two forward passes so `y = x + 1` after `x = jnp.sum(...)`
        # taints even with one-pass visiting order quirks
        traced: Set[str] = set()
        for _ in range(2):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and _tainted(sub.value,
                                                            traced):
                    for t in sub.targets:
                        self._taint_target(t, traced)
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)) \
                        and sub.value is not None \
                        and _tainted(sub.value, traced):
                    self._taint_target(sub.target, traced)
        self.traced_vars.append(traced)

    def exit_function(self, node):
        self.traced_vars.pop()

    @staticmethod
    def _taint_target(t, traced: Set[str]):
        if isinstance(t, ast.Name):
            traced.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                _TracedBranchVisitor._taint_target(e, traced)

    def _check_test(self, test, what: str):
        if not self.traced_vars or not self.stack:
            return
        if not self.is_traced_fn(".".join(self.stack)):
            return
        if _tainted(test, self.traced_vars[-1]):
            self.findings.append(self.sf.finding(
                "traced-branch", test,
                f"Python {what} on a traced value in jit-reachable "
                f"code — use lax.cond/jnp.where or hoist the check"))

    def visit_If(self, node):
        self._check_test(node.test, "branch")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_test(node.test, "while-loop")
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._check_test(node.test, "conditional expression")
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._check_test(node.test, "assert")
        self.generic_visit(node)


def check_traced_branch(sf: SourceFile, graph) -> List[Finding]:
    module = _module_name(sf.path)
    findings: List[Finding] = []
    v = _TracedBranchVisitor(
        sf, lambda qual: graph.is_traced(module, qual), findings)
    v.visit(sf.tree)
    return findings


# -------------------------------------------------------- default-dtype

class _DefaultDtypeVisitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, np_aliases: Set[str],
                 findings: List[Finding]):
        self.sf = sf
        self.np = np_aliases
        self.findings = findings

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in self.np:
            if f.attr in _NUMPY_CREATORS:
                has_dtype = any(kw.arg == "dtype" for kw in node.keywords) \
                    or any(_looks_like_dtype(a) for a in node.args)
                if not has_dtype:
                    self.findings.append(self.sf.finding(
                        "default-dtype", node,
                        f"np.{f.attr} without an explicit dtype defaults "
                        f"to float64/int64 in kernel code"))
                for a in node.args:
                    # a POSITIONAL float64 dtype must not escape just
                    # because it satisfied has_dtype
                    if self._is_float64(a):
                        self.findings.append(self.sf.finding(
                            "default-dtype", a,
                            "explicit float64 dtype in kernel code"))
            elif f.attr == "float64":
                self.findings.append(self.sf.finding(
                    "default-dtype", node,
                    "explicit float64 scalar in kernel code"))
            elif f.attr in ("asarray", "array") and node.args:
                for a in node.args[1:]:     # positional dtype
                    if self._is_float64(a):
                        self.findings.append(self.sf.finding(
                            "default-dtype", a,
                            "explicit float64 dtype in kernel code"))
                if self._bare_float_literal(node.args[0]) \
                        and not any(kw.arg == "dtype"
                                    for kw in node.keywords) \
                        and not any(_looks_like_dtype(a)
                                    for a in node.args[1:]):
                    self.findings.append(self.sf.finding(
                        "default-dtype", node,
                        "bare float literal arrayified at float64"))
        for kw in getattr(node, "keywords", []):
            if kw.arg == "dtype" and self._is_float64(kw.value):
                self.findings.append(self.sf.finding(
                    "default-dtype", kw.value,
                    "explicit float64 dtype in kernel code"))
        self.generic_visit(node)

    @staticmethod
    def _is_float64(node) -> bool:
        if isinstance(node, ast.Attribute):
            return node.attr == "float64"
        if isinstance(node, ast.Constant):
            return node.value in ("float64", "double")
        return False

    @staticmethod
    def _bare_float_literal(node) -> bool:
        """A float scalar, or a list/tuple literal containing one —
        numpy infers float64 for both."""
        if isinstance(node, ast.Constant):
            return isinstance(node.value, float)
        if isinstance(node, (ast.List, ast.Tuple)):
            return any(_DefaultDtypeVisitor._bare_float_literal(e)
                       for e in node.elts)
        return False


def check_default_dtype(sf: SourceFile, graph=None) -> List[Finding]:
    norm = sf.path.replace(os.sep, "/")
    if not any(norm.startswith(d + "/") or os.path.dirname(norm) == d
               for d in KERNEL_DIRS):
        return []
    findings: List[Finding] = []
    _DefaultDtypeVisitor(sf, _numpy_aliases(sf.tree) | {"np"},
                         findings).visit(sf.tree)
    return findings


# --------------------------------------------------------- metric-drift

def collect_metric_names(sources: Dict[str, str]) -> Dict[str, List]:
    """name -> [(path, line)] for every serving./resilience./decode.*
    metric literal created in the package. The ONE implementation both
    the lint rule and tests/test_slo.py use. Scans whole files (the
    ``\\s*`` crosses newlines), so a call wrapped for line length is
    still seen."""
    names: Dict[str, List] = {}
    for path, src in sources.items():
        for m in _METRIC_CALL.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            names.setdefault(m.group(1), []).append((path, line))
    return names


def check_metric_drift(sources: Dict[str, str], docs_text: str,
                       line_lookup) -> List[Finding]:
    findings = []
    for name, sites in sorted(collect_metric_names(sources).items()):
        if name in docs_text:
            continue
        for path, line in sites:
            findings.append(Finding(
                "metric-drift", path, line, 0,
                f"metric {name!r} is not documented in "
                f"docs/OBSERVABILITY.md", line_lookup(path, line)))
    return findings


# ----------------------------------------------------------- fault-site

def known_fault_sites(faults_source: str) -> Set[str]:
    """Parse resilience/faults.py for the KNOWN_SITES literal — the
    linter must not import the package (no jax import on the lint
    path)."""
    tree = ast.parse(faults_source)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "KNOWN_SITES":
                    return {e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)}
    return set()


def check_fault_site(sf: SourceFile, sites: Set[str]) -> List[Finding]:
    if sf.path.replace(os.sep, "/").endswith("resilience/faults.py"):
        return []       # the registry itself (defaults, docstrings)
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name not in ("maybe_fire", "Fault"):
            continue
        site = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            site = node.args[0].value
        else:
            for kw in node.keywords:
                if kw.arg == "site" and isinstance(kw.value, ast.Constant):
                    site = kw.value.value
        if site is not None and site not in sites:
            findings.append(sf.finding(
                "fault-site", node,
                f"fault site {site!r} is not registered in "
                f"resilience.faults.KNOWN_SITES"))
    return findings


# -------------------------------------------------------------- driver

def _module_name(path: str) -> str:
    module = os.path.splitext(path.replace(os.sep, "/"))[0].replace(
        "/", ".")
    if module.endswith(".__init__"):
        module = module[: -len(".__init__")]
    return module


ALL_RULES = ("host-sync", "traced-branch", "default-dtype",
             "metric-drift", "fault-site")


def run_rules(files: Dict[str, SourceFile], graph, docs_text: str,
              fault_sites: Set[str],
              rules=ALL_RULES) -> List[Finding]:
    findings: List[Finding] = []
    per_file = {"host-sync": lambda sf: check_host_sync(sf, graph),
                "traced-branch": lambda sf: check_traced_branch(sf, graph),
                "default-dtype": check_default_dtype,
                "fault-site": lambda sf: check_fault_site(sf, fault_sites)}
    for rule in rules:
        if rule == "metric-drift":
            sources = {p: sf.source for p, sf in files.items()}
            findings.extend(check_metric_drift(
                sources, docs_text,
                lambda p, ln: files[p].line_text(ln)))
            continue
        fn = per_file[rule]
        for sf in files.values():
            findings.extend(fn(sf))
    findings.sort(key=Finding.sort_key)
    return findings
