"""Runtime dispatch sanitizer: transfer and recompile guards.

The static pass (analysis/lint.py) sees where code *could* sync; this
module enforces what a region *actually does* at runtime:

* :func:`no_transfer` — a context in which implicit AND explicit
  host->device transfers raise (``jax.transfer_guard_host_to_device
  ("disallow_explicit")``): the enforcement form of the serving
  engine's "no steady-state H2D" claim. D2H is allowed by default —
  the one sampled-token pull per step IS the completion fence — and
  guardable with ``d2h=True``. (On the CPU backend D2H is zero-copy
  and the guard never fires; H2D fires at jit argument placement and
  ``jnp.asarray`` alike, so the invariant is testable without a TPU.)
* :func:`no_recompile` / :func:`count_compiles` — XLA backend-compile
  events captured via ``jax.monitoring`` (one
  ``/jax/core/compile/backend_compile_duration`` event per real
  compile; jit-cache hits emit nothing): a region that claims "warm"
  must compile nothing.
* :func:`sanitize` — both at once; what ``ServingEngine(sanitize=True)``
  wraps steady-state dispatches in and the benches arm under
  ``--sanitize``.
* :func:`snapshot_roundtrip` — the STATE-protocol guard (the runtime
  half of the ``snapshot-coverage`` lint rule): snapshot → restore →
  snapshot must be byte-identical in canonical form, or a serialized
  field is rotting. ``ServingEngine(sanitize="roundtrip"|"all")`` runs
  it on every ``save_snapshot``; ``chaos_bench --roundtrip_every N``
  exercises it mid-soak.

Guards compose with ``with`` nesting and are thread-visible the way
jax's own context managers are; the compile listener is registered
once, process-wide, and costs one list-append per *compile* (never on
a cache-hit dispatch), so leaving it registered is free on the hot
path.
"""

import json
import re
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional

import jax

__all__ = ["CompileCounter", "DonationError", "DonationReport",
           "RecompileError", "SnapshotDriftError", "TransferError",
           "canonical_snapshot", "canonical_snapshot_bytes",
           "compare_snapshots", "count_compiles", "donation_report",
           "no_recompile", "no_transfer", "sanitize",
           "snapshot_roundtrip", "compile_events_supported"]

#: the monitoring event one real XLA backend compile emits (jax 0.4+);
#: trace-only events (jaxpr_trace) deliberately NOT counted — a
#: retrace that hits the compile cache costs µs, a backend compile
#: costs seconds
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class RecompileError(RuntimeError):
    """A ``no_recompile`` region compiled."""


class TransferError(RuntimeError):
    """Raised by :func:`no_transfer` wrapping for a uniform excepting
    type; the underlying jax error is chained as ``__cause__``."""


class CompileCounter:
    """Collects backend-compile events while registered as active."""

    __slots__ = ("events",)

    def __init__(self):
        self.events: List[str] = []

    @property
    def count(self) -> int:
        return len(self.events)


_active_counters: List[CompileCounter] = []
_listener_lock = threading.Lock()
_listener_state = {"registered": False, "supported": None}


def _on_event(name: str, dur: float, **kwargs):
    if name == BACKEND_COMPILE_EVENT and _active_counters:
        for c in list(_active_counters):
            c.events.append(name)


def _ensure_listener() -> bool:
    with _listener_lock:
        if not _listener_state["registered"]:
            try:
                from jax import monitoring
                monitoring.register_event_duration_secs_listener(_on_event)
                _listener_state["supported"] = True
            except Exception:   # pragma: no cover - jax too old
                _listener_state["supported"] = False
            _listener_state["registered"] = True
    return bool(_listener_state["supported"])


def compile_events_supported() -> bool:
    """Whether this jax exposes the monitoring seam the compile guards
    need (True on the supported 0.4.x/0.9 fleet)."""
    return _ensure_listener()


@contextmanager
def count_compiles():
    """``with count_compiles() as c: ...; c.count`` — the number of XLA
    backend compiles the block performed."""
    _ensure_listener()
    c = CompileCounter()
    _active_counters.append(c)
    try:
        yield c
    finally:
        _active_counters.remove(c)


@contextmanager
def no_recompile(allow: int = 0, what: str = "region"):
    """Raise :class:`RecompileError` if the block backend-compiles more
    than ``allow`` programs. The expected-compile form (``allow=n``)
    pins e.g. "a join at a NEW prompt shape compiles exactly one
    prefill program"."""
    with count_compiles() as c:
        yield c
    if c.count > allow:
        raise RecompileError(
            f"{what} compiled {c.count} program(s) "
            f"(allowed {allow}) — a warm hot path must not recompile; "
            f"shapes or static arguments are churning")


@contextmanager
def no_transfer(h2d: bool = True, d2h: bool = False, d2d: bool = False,
                what: str = "region"):
    """Disallow device transfers inside the block (explicit AND
    implicit — a ``jnp.asarray`` upload and a jit-argument placement
    both count). Violations raise jax's ``XlaRuntimeError`` at the
    transfer site, chained into :class:`TransferError` with the region
    name."""
    ctxs = []
    if h2d:
        ctxs.append(jax.transfer_guard_host_to_device("disallow_explicit"))
    if d2h:
        ctxs.append(jax.transfer_guard_device_to_host("disallow_explicit"))
    if d2d:
        ctxs.append(
            jax.transfer_guard_device_to_device("disallow_explicit"))
    try:
        for c in ctxs:
            c.__enter__()
        try:
            yield
        finally:
            for c in reversed(ctxs):
                c.__exit__(None, None, None)
    except Exception as e:
        if "Disallowed" in str(e) and "transfer" in str(e):
            raise TransferError(
                f"{what} performed a guarded device transfer: {e}") from e
        raise


@contextmanager
def sanitize(what: str = "region", h2d: bool = True, d2h: bool = False,
             allow_compiles: int = 0):
    """The combined guard: no H2D transfers (optionally D2H) and no
    backend compiles. The ``ServingEngine(sanitize=True)`` steady-state
    contract and the benches' ``--sanitize`` wrap."""
    with no_transfer(h2d=h2d, d2h=d2h, what=what), \
            no_recompile(allow=allow_compiles, what=what):
        yield


# ----------------------------------------------------- donation report

class DonationError(RuntimeError):
    """A ``DonationReport.expect_aliased`` pin failed: an input the
    program was expected to alias into an output is being copied."""


#: one `{out...}: (param, {...}, kind)` entry in the compiled HLO
#: module header's input_output_alias table
_ALIAS_ENTRY = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[^}]*\},\s*([a-z-]+)\)")


def _alias_table(hlo_text: str) -> str:
    """The brace-balanced body of ``input_output_alias={...}`` in a
    compiled module header ('' when the program aliases nothing)."""
    key = "input_output_alias={"
    i = hlo_text.find(key)
    if i < 0:
        return ""
    depth, j = 1, i + len(key)
    while j < len(hlo_text) and depth:
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
        j += 1
    return hlo_text[i + len(key):j - 1]


def _entry_param_types(hlo_text: str) -> List[str]:
    """Layout-stripped parameter type strings ('bf16[2,34,32,128]')
    from the compiled module's entry_computation_layout, in parameter
    order. The OPTIMIZED module's parameter numbering — XLA dead-codes
    unused inputs and renumbers — so alias entries must be matched to
    jax-level arguments by type, not by flat position."""
    m = re.search(r"entry_computation_layout=\{\((.*?)\)->", hlo_text)
    if not m:
        return []
    out = []
    for tok in m.group(1).split(", "):
        tok = re.sub(r"/\*[^*]*\*/", "", tok)       # /*index=N*/
        out.append(re.sub(r"\{[^}]*\}", "", tok).strip())
    return out


#: numpy dtype name -> HLO primitive-type name
_HLO_DTYPES = {"float32": "f32", "float64": "f64", "float16": "f16",
               "bfloat16": "bf16", "int8": "s8", "int16": "s16",
               "int32": "s32", "int64": "s64", "uint8": "u8",
               "uint16": "u16", "uint32": "u32", "uint64": "u64",
               "bool": "pred", "complex64": "c64", "complex128": "c128"}


def _aval_type(aval) -> str:
    dt = _HLO_DTYPES.get(str(aval.dtype), str(aval.dtype))
    return f"{dt}[{','.join(str(d) for d in aval.shape)}]"


class DonationReport:
    """What ONE lowered+compiled program actually does with its
    inputs: per python-argnum leaf counts, how many leaves the caller
    DECLARED donated (``jax.jit(..., donate_argnums=)``), and how many
    XLA actually wired into the input_output_alias table (with the
    alias kind — ``may-alias``/``must-alias``). The static half of the
    donation story is the ``donation`` lint rule; this is the runtime
    proof that "the TPU path aliases the carry away" — or the evidence
    that a backend quietly copies instead."""

    __slots__ = ("what", "args", "alias_kinds")

    def __init__(self, what: str):
        self.what = what
        #: argnum -> {"leaves", "donated", "aliased"}
        self.args: Dict[int, Dict] = {}
        self.alias_kinds: List[str] = []

    @property
    def donated_argnums(self) -> List[int]:
        return sorted(a for a, d in self.args.items() if d["donated"])

    @property
    def aliased_argnums(self) -> List[int]:
        return sorted(a for a, d in self.args.items() if d["aliased"])

    def fully_aliased(self, argnum: int) -> bool:
        d = self.args.get(argnum)
        return bool(d) and d["aliased"] == d["leaves"]

    def expect_aliased(self, *argnums: int):
        """Assert every listed argnum has ALL its leaves aliased into
        outputs — the test-pin form. Returns self for chaining."""
        for a in argnums:
            if not self.fully_aliased(a):
                d = self.args.get(a, {"leaves": 0, "donated": 0,
                                      "aliased": 0})
                raise DonationError(
                    f"{self.what}: argnum {a} expected input->output "
                    f"aliasing but got {d['aliased']}/{d['leaves']} "
                    f"leaves aliased ({d['donated']} declared donated) "
                    f"— the dispatch copies this buffer")
        return self

    def __repr__(self):
        rows = ", ".join(
            f"{a}: {d['aliased']}/{d['leaves']} aliased"
            f"{' (donated)' if d['donated'] else ''}"
            for a, d in sorted(self.args.items()))
        return f"DonationReport({self.what}: {rows})"


def donation_report(fn, *args, static_argnums=(), what="program",
                    **kwargs) -> DonationReport:
    """Lower AND compile ``fn(*args, **kwargs)`` and report which
    inputs actually aliased outputs — the runtime half of the
    ``donation`` lint rule (docs/ANALYSIS.md §donation).

    ``fn`` is a jitted callable (anything with ``.lower``), or an
    engine program handle carrying ``.jitted``/``.bound`` attributes
    (the serving engine's step/verify/chunk lambdas expose these so
    test pins can audit the live programs with their bound state).
    ``static_argnums`` must repeat the jit wrapper's, so flat
    parameters map back to the right python argnums. Argnums are
    positions in the LOWERED call — bound leading arguments included.

    The declared side comes from ``Lowered.args_info`` (per-leaf
    ``donated`` flags); the actual side is parsed from the compiled
    module's ``input_output_alias`` header — one entry per flat
    parameter XLA wired to an output buffer. A backend that drops
    donation (old-jax CPU) shows declared > aliased, which is exactly
    the BENCH_r06 chunked-capacity caveat made visible."""
    target = fn
    bound = ()
    if not hasattr(target, "lower"):
        jitted = getattr(fn, "jitted", None)
        if jitted is None:
            raise TypeError(
                f"donation_report needs a jitted callable (or an "
                f"engine program handle with .jitted/.bound); got "
                f"{type(fn).__name__}")
        b = getattr(fn, "bound", ())
        bound = tuple(b() if callable(b) else b)
        target = jitted
    lowered = target.lower(*bound, *args, **kwargs)
    compiled = lowered.compile()

    report = DonationReport(what)
    info_args, _info_kwargs = lowered.args_info
    statics = set(static_argnums)
    # python argnums of the DYNAMIC positional args, in order (statics
    # never reach args_info or the parameter list)
    n_total = len(info_args) + len(statics)
    dyn_argnums = [i for i in range(n_total) if i not in statics]

    # the OPTIMIZED module renumbers parameters (DCE drops unused
    # inputs — the step program dead-codes most state leaves), so
    # alias entries map back to jax arguments by TYPE: only donated
    # leaves are alias candidates. Identically-typed donated leaves
    # are indistinguishable in the table, so a type is credited only
    # when the aliased supply covers EVERY donated leaf of that type —
    # a partially-aliased ambiguous type counts as copied for all of
    # them (expect_aliased fails closed instead of false-passing on
    # whichever argnum is visited first).
    hlo = compiled.as_text()
    param_types = _entry_param_types(hlo)
    aliased_types: Dict[str, int] = {}
    for entry in _ALIAS_ENTRY.finditer(_alias_table(hlo)):
        idx = int(entry.group(1))
        report.alias_kinds.append(entry.group(2))
        if idx < len(param_types):
            t = param_types[idx]
            aliased_types[t] = aliased_types.get(t, 0) + 1

    # a SHARDED module's entry layout lists per-shard parameter shapes,
    # so each leaf's matching type is its LOCAL shape under the actual
    # argument's sharding (shard_shape) — matching global avals instead
    # would make every sharded donated buffer look copied. The real
    # argument leaves align with args_info's dynamic trees; unsharded
    # arrays degrade to the global shape (SingleDeviceSharding's
    # shard_shape is the identity).
    all_pos = list(bound) + list(args)
    value_leaves = []
    for i in dyn_argnums:
        value_leaves.extend(jax.tree_util.tree_leaves(all_pos[i]))

    def _leaf_type(leaf, flat_i: int) -> Optional[str]:
        aval = getattr(leaf, "_aval", None) or getattr(leaf, "aval",
                                                       None)
        if aval is None:
            return None
        shape = tuple(aval.shape)
        if flat_i < len(value_leaves):
            sh = getattr(value_leaves[flat_i], "sharding", None)
            if sh is not None:
                try:
                    shape = tuple(sh.shard_shape(shape))
                except Exception:   # noqa: BLE001 — keep global shape
                    pass
        dt = _HLO_DTYPES.get(str(aval.dtype), str(aval.dtype))
        return f"{dt}[{','.join(str(d) for d in shape)}]"

    donated_demand: Dict[str, int] = {}
    flat_i = 0
    for tree in info_args:
        for leaf in jax.tree_util.tree_leaves(tree):
            if getattr(leaf, "donated", False):
                t = _leaf_type(leaf, flat_i)
                if t is not None:
                    donated_demand[t] = donated_demand.get(t, 0) + 1
            flat_i += 1

    flat_i = 0
    for argnum, tree in zip(dyn_argnums, info_args):
        leaves = jax.tree_util.tree_leaves(tree)
        donated = aliased = 0
        for leaf in leaves:
            if getattr(leaf, "donated", False):
                donated += 1
                t = _leaf_type(leaf, flat_i)
                if t is not None and aliased_types.get(t, 0) \
                        >= donated_demand.get(t, 0):
                    aliased += 1
            flat_i += 1
        report.args[argnum] = {"leaves": len(leaves),
                               "donated": donated, "aliased": aliased}
    return report


# ------------------------------------------------- snapshot round trip

class SnapshotDriftError(RuntimeError):
    """snapshot -> restore -> snapshot was not byte-identical in
    canonical form: a serialized field is being lost, re-derived
    differently, or restored asymmetrically."""


def canonical_snapshot(snap: Dict) -> Dict:
    """The canonical form of a ``paddle_tpu.engine_snapshot/v1`` dict:
    everything the protocol promises to round-trip, nothing that is
    volatile by contract. Slots and queue merge into ONE scheduling-
    ordered request list — a just-restored engine holds every request
    in its queue, so slot-vs-queue placement is scheduling state, not
    protocol state. Excluded as volatile BY CONTRACT (docs/SERVING.md
    §Snapshot contract): ``ts`` (wall clock), ``step_seq`` (restore
    bumps it), ``prefix_keys`` (postmortem info; the cache rebuilds
    from traffic), per-request ``chunk_filled`` (restore re-prefills
    from tokens) and ``deadline_remaining_s`` (re-anchored to the
    restore wall clock — only its None-ness is protocol state), and
    the ``sanitize``/``flight_dump_path`` config knobs (debug guard
    and postmortem sink — the roundtrip itself restores with the guard
    off and the sink detached)."""
    from paddle_tpu.serving.engine import _PRIORITY_RANK

    reqs = []
    for e in list(snap.get("slots", ())) + list(snap.get("queue", ())):
        d = {k: v for k, v in e.items()
             if k not in ("chunk_filled", "deadline_remaining_s")}
        d["has_deadline"] = e.get("deadline_remaining_s") is not None
        reqs.append(d)
    reqs.sort(key=lambda d: (-_PRIORITY_RANK.get(d.get("priority",
                                                       "normal"), 1),
                             d.get("seq", 0)))
    results = sorted(snap.get("results", ()),
                     key=lambda r: r["request_id"])
    config = {k: v for k, v in snap.get("config", {}).items()
              if k not in ("sanitize", "flight_dump_path")}
    return {"schema": snap.get("schema"), "config": config,
            "model": snap.get("model"), "requests": reqs,
            "results": results,
            "seeds_issued": snap.get("seeds_issued"),
            "submit_seq": snap.get("submit_seq")}


def canonical_snapshot_bytes(snap: Dict) -> bytes:
    return json.dumps(canonical_snapshot(snap), sort_keys=True,
                      separators=(",", ":")).encode()


def compare_snapshots(snap1: Dict, snap2: Dict,
                      what: str = "snapshot roundtrip"):
    """Raise :class:`SnapshotDriftError` naming the first diverging
    canonical section when the two snapshots differ."""
    c1, c2 = canonical_snapshot(snap1), canonical_snapshot(snap2)
    if c1 == c2:
        return
    for key in c1:
        if c1[key] != c2[key]:
            raise SnapshotDriftError(
                f"{what}: canonical section {key!r} diverged —\n"
                f"  before restore: {json.dumps(c1[key], sort_keys=True)[:400]}\n"
                f"  after restore:  {json.dumps(c2[key], sort_keys=True)[:400]}")
    raise SnapshotDriftError(f"{what}: snapshots diverged "
                             f"(keys {sorted(c1)} vs {sorted(c2)})")


def snapshot_roundtrip(engine, snap: Optional[Dict] = None):
    """The state-protocol sanitizer: assert that restoring ``engine``'s
    snapshot and re-snapshotting reproduces the SAME canonical bytes —
    no field silently lost, none re-derived differently. Builds a real
    restored engine (its own pool + programs) and closes it, so this is
    a debug/chaos tier, not a hot-path guard. Returns the verified
    snapshot. Raises :class:`SnapshotDriftError` on drift.

    Wired in: ``ServingEngine(sanitize="roundtrip"|"all")`` runs this
    inside every ``save_snapshot`` (the snapshot you are about to trust
    is the one checked), and ``examples/chaos_bench.py
    --roundtrip_every N`` calls it mid-soak."""
    from paddle_tpu.observability import registry

    snap1 = snap if snap is not None else engine.snapshot()
    # the restored twin must neither recurse the roundtrip nor dump
    # into the live engine's flight sink; the draft proposer's model
    # does not serialize, so hand the live SpecConfig back
    overrides = dict(sanitize=False, flight_dump_path=None)
    if getattr(engine, "speculate", None) is not None:
        overrides["speculate"] = engine.speculate
    # snapshots are mesh-free: the twin must be re-handed the live
    # engine's mesh/layout or it would restore single-device and the
    # roundtrip would "pass" without exercising the sharded paths
    if getattr(engine, "mesh", None) is not None:
        overrides["mesh"] = engine.mesh
        overrides["layout"] = engine.layout
    eng2 = type(engine).restore(engine.model, snap1,
                                state=engine._state, **overrides)
    try:
        snap2 = eng2.snapshot()
    finally:
        eng2.close()
    compare_snapshots(snap1, snap2)
    engine.stats["roundtrip_checks"] = (
        engine.stats.get("roundtrip_checks", 0) + 1)
    registry().counter("serving.snapshot_roundtrips").inc()
    return snap1
