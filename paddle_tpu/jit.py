"""paddle.jit parity: to_static / save / load.

Reference (SURVEY.md §2.7-dy2static): @to_static rewrites Python AST into a
static Program cached per input-spec; jit.save exports an inference model.
TPU-native: jax traces Python directly, so to_static IS jax.jit (with
lax.cond/scan for data-dependent control flow); save/load export a
state_dict + a layer-config pickle that Predictor/load can rehydrate.
"""

import os
import pickle

from paddle_tpu.framework.grad import jit, no_grad, to_static  # noqa: F401
from paddle_tpu.framework import io as _io


class TranslatedLayer:
    """Loaded inference bundle: state + jitted apply (≈ jit.load result)."""

    def __init__(self, model, state):
        import jax
        from paddle_tpu.nn.layer import functional_call
        self._model = model
        self._state = state
        self._fwd = jax.jit(lambda st, *a, **k: functional_call(
            model, st, *a, **k))

    def __call__(self, *args, **kwargs):
        return self._fwd(self._state, *args, **kwargs)

    @property
    def model(self):
        return self._model


def save(layer, path, input_spec=None):
    """Export `layer` for inference: {path}.pdparams + {path}.pdmodel
    (a pickled (class, config) pair when the layer exposes `.cfg`)."""
    _io.save(layer.state_dict(), path + ".pdparams")
    meta = {"class": type(layer).__module__ + "." + type(layer).__qualname__}
    cfg = getattr(layer, "cfg", None)
    if cfg is not None:
        meta["config"] = cfg
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


def load(path, model=None):
    """Rehydrate a saved layer; pass `model` to skip class lookup."""
    state = _io.load(path + ".pdparams")
    if model is None:
        with open(path + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
        mod_name, _, cls_name = meta["class"].rpartition(".")
        import importlib
        cls = getattr(importlib.import_module(mod_name), cls_name)
        model = cls(meta["config"]) if "config" in meta else cls()
    model.set_state_dict(state)
    model.eval()
    return TranslatedLayer(model, model.state_dict())
