"""Loss layers (ref: python/paddle/nn/layer/loss.py)."""

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, reduction="mean", soft_label=False, ignore_index=-100,
                 label_smoothing=0.0, axis=-1):
        super().__init__()
        self.reduction = reduction
        self.soft_label = soft_label
        self.ignore_index = ignore_index
        self.label_smoothing = label_smoothing
        self.axis = axis

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction=self.reduction,
                               soft_label=self.soft_label,
                               ignore_index=self.ignore_index,
                               label_smoothing=self.label_smoothing, axis=self.axis)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)
