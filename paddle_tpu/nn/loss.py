"""Loss layers (ref: python/paddle/nn/layer/loss.py)."""

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, reduction="mean", soft_label=False, ignore_index=-100,
                 label_smoothing=0.0, axis=-1):
        super().__init__()
        self.reduction = reduction
        self.soft_label = soft_label
        self.ignore_index = ignore_index
        self.label_smoothing = label_smoothing
        self.axis = axis

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction=self.reduction,
                               soft_label=self.soft_label,
                               ignore_index=self.ignore_index,
                               label_smoothing=self.label_smoothing, axis=self.axis)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean"):
        super().__init__()
        self.margin, self.p, self.epsilon = margin, p, epsilon
        self.swap, self.reduction = swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean"):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class CTCLoss(Layer):
    """CTC (reference: paddle.nn.CTCLoss over the warpctc kernel)."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class RNNTLoss(Layer):
    """RNN-Transducer loss (reference paddle.nn.RNNTLoss / warprnnt)."""

    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, logits, labels, input_lengths, label_lengths):
        return F.rnnt_loss(logits, labels, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)
