"""Layer — the module system, and its functional bridge to jax.jit.

The reference's `paddle.nn.Layer` (ref: python/paddle/nn/layer/layers.py) is a
stateful module tree: parameters/buffers/sublayers registered by attribute
assignment, `state_dict`/`set_state_dict`, forward hooks, train/eval modes.

TPU-first design: the same stateful authoring UX, but parameters are jax
Arrays and the whole tree is one pytree. Training runs through the functional
bridge — `functional_call(layer, state, *args)` temporarily binds `state`
(a flat {qualified_name: array} dict) into the tree, runs forward, and
restores. Under `jax.jit` tracing this yields a pure function of the state,
so `jax.grad`/`jax.value_and_grad` and GSPMD shardings apply directly; the
per-op dispatch loop the reference runs every step exists here only at trace
time (SURVEY.md §3.1).
"""

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtype import to_jax_dtype, is_floating
from paddle_tpu.core import rng as rng_mod


_LAZY = [False]


class LazyGuard:
    """Meta-init context (reference paddle.LazyGuard): layers constructed
    inside allocate NO parameter buffers — every Parameter holds a
    jax.ShapeDtypeStruct. The resulting model supports shape/pspec queries,
    `pipeline_parts()`, and the AOT `step_fn.lower()` feasibility path
    (SCALE.md), but not execution (`init_fn`/forward need real buffers).
    This is how a 65B model's full training program compiles on a host
    that cannot hold 65B weights."""

    def __enter__(self):
        self._prev = _LAZY[0]
        _LAZY[0] = True
        return self

    def __exit__(self, *exc):
        _LAZY[0] = self._prev
        return False


class Parameter:
    """A named, trainable-flagged slot holding a jax Array."""

    __slots__ = ("value", "trainable", "name", "is_distributed", "pspec")

    def __init__(self, value, trainable=True, name=None, pspec=None):
        self.value = value
        self.trainable = trainable
        self.name = name
        self.is_distributed = False
        # PartitionSpec placement hint consumed by fleet/auto_parallel
        # (≈ the reference's TensorDistAttr dims_mapping on DistTensor)
        self.pspec = pspec

    @property
    def shape(self):
        return self.value.shape

    @property
    def dtype(self):
        return self.value.dtype

    def __repr__(self):
        return f"Parameter(shape={tuple(self.value.shape)}, dtype={self.value.dtype}, trainable={self.trainable})"


class Layer:
    """Base class for all network modules (``paddle.nn.Layer`` parity)."""

    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_forward_pre_hooks", OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", OrderedDict())
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_dtype", to_jax_dtype(dtype))
        object.__setattr__(self, "_name_scope", name_scope or type(self).__name__)

    # -- registration --------------------------------------------------------

    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self._parameters[name] = value
            self._buffers.pop(name, None)
            self._sub_layers.pop(name, None)
        elif isinstance(value, Layer):
            self._sub_layers[name] = value
            self._parameters.pop(name, None)
            self._buffers.pop(name, None)
        else:
            if name in self._parameters:
                # assigning an array onto a parameter slot updates it in place
                if isinstance(value, (jax.Array, np.ndarray)):
                    self._parameters[name].value = jnp.asarray(value)
                    return
                del self._parameters[name]
            if name in self._buffers:
                if isinstance(value, (jax.Array, np.ndarray)):
                    self._buffers[name] = jnp.asarray(value)
                    return
                del self._buffers[name]
            self._sub_layers.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        d = self.__dict__
        if name in d.get("_parameters", ()):
            return d["_parameters"][name].value
        if name in d.get("_buffers", ()):
            return d["_buffers"][name]
        if name in d.get("_sub_layers", ()):
            return d["_sub_layers"][name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in (self._parameters, self._buffers, self._sub_layers):
            if name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    def create_parameter(self, shape, dtype=None, default_initializer=None,
                         is_bias=False, attr=None, trainable=True):
        """Create + register-ready Parameter (assign it to an attribute)."""
        from paddle_tpu.nn import initializer as init
        dtype = to_jax_dtype(dtype) if dtype is not None else self._dtype
        if _LAZY[0]:
            # LazyGuard (reference paddle.LazyGuard): META init — no
            # buffer is ever allocated; the Parameter carries only
            # shape/dtype (+ pspec set later by TP layers). Used to build
            # pod-scale models (65B) for AOT feasibility compiles on
            # hosts that can't hold their weights.
            return Parameter(jax.ShapeDtypeStruct(tuple(shape), dtype),
                             trainable=trainable)
        if default_initializer is None:
            default_initializer = init.Constant(0.0) if is_bias else init.XavierNormal()
        value = default_initializer(shape, dtype)
        return Parameter(value, trainable=trainable)

    def add_parameter(self, name, param):
        self._parameters[name] = param
        return param

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = jnp.asarray(tensor) if tensor is not None else None

    # -- traversal -----------------------------------------------------------

    def named_sublayers(self, prefix="", include_self=False) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            p = f"{prefix}.{name}" if prefix else name
            yield p, sub
            yield from sub.named_sublayers(prefix=p)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix="") -> Iterator[Tuple[str, Parameter]]:
        for name, p in self._parameters.items():
            yield (f"{prefix}.{name}" if prefix else name), p
        for name, sub in self._sub_layers.items():
            sp = f"{prefix}.{name}" if prefix else name
            yield from sub.named_parameters(prefix=sp)

    def parameters(self, include_sublayers=True):
        if include_sublayers:
            return [p for _, p in self.named_parameters()]
        return list(self._parameters.values())

    def named_buffers(self, prefix=""):
        for name, b in self._buffers.items():
            yield (f"{prefix}.{name}" if prefix else name), b
        for name, sub in self._sub_layers.items():
            sp = f"{prefix}.{name}" if prefix else name
            yield from sub.named_buffers(prefix=sp)

    def buffers(self):
        return [b for _, b in self.named_buffers()]

    # -- state dict ----------------------------------------------------------

    def state_dict(self, include_buffers=True) -> Dict[str, jax.Array]:
        # plain dict: insertion-ordered and pytree-compatible with the plain
        # dicts produced by optimizers/grads (OrderedDict has a distinct treedef)
        out = {}
        for name, p in self.named_parameters():
            out[name] = p.value
        if include_buffers:
            for name, b in self.named_buffers():
                if b is not None:
                    out[name] = b
        return out

    def trainable_state(self) -> Dict[str, jax.Array]:
        return {n: p.value for n, p in self.named_parameters() if p.trainable}

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        params = dict(self.named_parameters())
        buffer_owners = self._buffer_owners()
        for k, v in state_dict.items():
            v = jnp.asarray(v)
            if k in params:
                params[k].value = v.astype(params[k].value.dtype)
            elif k in buffer_owners:
                owner, local = buffer_owners[k]
                owner._buffers[local] = v
            else:
                unexpected.append(k)
        for k in params:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    def _buffer_owners(self):
        out = {}
        for prefix, layer in self.named_sublayers(include_self=True, prefix=""):
            for name in layer._buffers:
                out[f"{prefix}.{name}" if prefix else name] = (layer, name)
        return out

    # -- modes / transforms --------------------------------------------------

    def train(self):
        object.__setattr__(self, "training", True)
        for l in self.sublayers():
            object.__setattr__(l, "training", True)
        return self

    def eval(self):
        object.__setattr__(self, "training", False)
        for l in self.sublayers():
            object.__setattr__(l, "training", False)
        return self

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None):
        if dtype is not None:
            dt = to_jax_dtype(dtype)
            for _, p in self.named_parameters():
                if is_floating(p.value.dtype):
                    if isinstance(p.value, jax.ShapeDtypeStruct):
                        p.value = jax.ShapeDtypeStruct(p.value.shape, dt)
                    else:
                        p.value = p.value.astype(dt)
            for prefix, layer in self.named_sublayers(include_self=True):
                for name, b in layer._buffers.items():
                    if b is not None and is_floating(b.dtype):
                        layer._buffers[name] = b.astype(dt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def bfloat16(self):
        return self.to(dtype=jnp.bfloat16)

    def float(self):
        return self.to(dtype=jnp.float32)

    # -- hooks ---------------------------------------------------------------

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- call ----------------------------------------------------------------

    def forward(self, *args, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()")

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, args)
            if out is not None:
                args = out if isinstance(out, tuple) else (out,)
        y = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, args, y)
            if out is not None:
                y = out
        return y

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else f"{type(self).__name__}()"

    # extra_repr parity
    def full_name(self):
        return self._name_scope


class _HookHandle:
    _next_id = [0]

    def __init__(self, store):
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        self._store = store

    def remove(self):
        self._store.pop(self.id, None)


# ---- functional bridge -----------------------------------------------------

@contextlib.contextmanager
def _bind_state(layer: Layer, state: Dict[str, jax.Array]):
    """Temporarily swap arrays from `state` into the layer tree."""
    params = dict(layer.named_parameters())
    buffer_owners = layer._buffer_owners()
    saved = []
    try:
        for k, v in state.items():
            if k in params:
                saved.append(("p", params[k], params[k].value))
                params[k].value = v
            elif k in buffer_owners:
                owner, local = buffer_owners[k]
                saved.append(("b", (owner, local), owner._buffers[local]))
                owner._buffers[local] = v
            else:
                raise KeyError(f"state key {k!r} not found in {type(layer).__name__}")
        yield
    finally:
        for kind, slot, old in reversed(saved):
            if kind == "p":
                slot.value = old
            else:
                owner, local = slot
                owner._buffers[local] = old


def functional_call(layer: Layer, state: Dict[str, jax.Array], *args,
                    rngs: Optional[Dict[str, jax.Array]] = None,
                    mutable: bool = False, method: Optional[str] = None,
                    **kwargs):
    """Run ``layer(*args)`` with `state` bound in — a pure function of `state`.

    With ``mutable=True`` returns ``(out, new_buffers)`` where `new_buffers`
    is the post-call value of every buffer (e.g. batchnorm running stats).
    ``method`` calls a named method instead of ``forward`` (e.g. a model's
    fused ``train_loss``).
    """
    with _bind_state(layer, state):
        with rng_mod.rng_guard(rngs or {}):
            fn = layer if method is None else getattr(layer, method)
            out = fn(*args, **kwargs)
            if mutable:
                new_buffers = {n: b for n, b in layer.named_buffers()
                               if b is not None}
                return out, new_buffers
    return out


def make_apply(layer: Layer) -> Callable:
    """Return ``apply(state, *args, rngs=None) -> out`` — the jit-ready forward."""
    def apply(state, *args, rngs=None, **kwargs):
        return functional_call(layer, state, *args, rngs=rngs, **kwargs)
    return apply
