"""Weight initializers (`paddle.nn.initializer` parity).

Ref: python/paddle/nn/initializer/ — Constant, Normal, TruncatedNormal, Uniform,
XavierNormal/Uniform, KaimingNormal/Uniform, Assign. Initializers are callables
`(shape, dtype) -> Array`, drawing from the global RNG (respecting `paddle_tpu.seed`).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import rng as _rng


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]  # Linear weight layout (in, out)
    # conv kernels: (out_ch, in_ch, *spatial) layout (see nn/layers/conv.py)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype=dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        arr = jnp.asarray(self.value, dtype=dtype)
        assert tuple(arr.shape) == tuple(shape), (arr.shape, shape)
        return arr


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        k = _rng.next_rng_key("params")
        return (self.mean + self.std *
                jax.random.normal(k, tuple(shape))).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        k = _rng.next_rng_key("params")
        return (self.mean + self.std *
                jax.random.truncated_normal(k, -2.0, 2.0, tuple(shape))).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        k = _rng.next_rng_key("params")
        return jax.random.uniform(k, tuple(shape), minval=self.low,
                                  maxval=self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        fan_in, fan_out = _fan_in_out(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        k = _rng.next_rng_key("params")
        return (std * jax.random.normal(k, tuple(shape))).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        fan_in, fan_out = _fan_in_out(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        k = _rng.next_rng_key("params")
        return jax.random.uniform(k, tuple(shape), minval=-limit,
                                  maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=jnp.float32):
        fan_in = self.fan_in or _fan_in_out(shape)[0]
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fan_in)
        k = _rng.next_rng_key("params")
        return (std * jax.random.normal(k, tuple(shape))).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=jnp.float32):
        fan_in = self.fan_in or _fan_in_out(shape)[0]
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fan_in)
        k = _rng.next_rng_key("params")
        return jax.random.uniform(k, tuple(shape), minval=-limit,
                                  maxval=limit).astype(dtype)


class Dirac(Initializer):
    """Identity-preserving conv init (reference paddle.nn.initializer.Dirac):
    out channel i passes through in channel i at the kernel center."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        w = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        ocpg = oc // self.groups
        center = tuple(s // 2 for s in shape[2:])
        # per group g, diagonal d < min(oc_per_group, in_channels)
        for g in range(self.groups):
            for d in range(min(ocpg, ic)):
                w[(g * ocpg + d, d) + center] = 1.0
        return jnp.asarray(w, dtype)


class Orthogonal(Initializer):
    """Orthogonal matrix init (reference paddle.nn.initializer.Orthogonal)."""

    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        k = _rng.next_rng_key("params")
        rows, cols = shape[0], int(np.prod(shape[1:]))
        q = jax.nn.initializers.orthogonal(self.gain, column_axis=-1)(
            k, (rows, cols), jnp.float32)
        return q.reshape(shape).astype(dtype)
