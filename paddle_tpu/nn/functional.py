"""Functional NN ops (`paddle.nn.functional` parity).

Ref: python/paddle/nn/functional/ — activations, linear, conv, pooling, norm,
loss, attention. Each op is a jnp/lax composition that XLA fuses; the hot fused
paths (flash attention, rms_norm, rope) additionally have Pallas TPU kernels in
`paddle_tpu.ops`, which these wrappers dispatch to when profitable.
"""

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import rng as _rng


# ---- activations -----------------------------------------------------------

def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jnp.minimum(jax.nn.relu(x), 6.0)


def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def silu(x):
    return jax.nn.silu(x)


swish = silu


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def hardsigmoid(x):
    return jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def softplus(x, beta=1.0):
    return jax.nn.softplus(beta * x) / beta


def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


# ---- linear / embedding ----------------------------------------------------

def linear(x, weight, bias=None):
    """y = x @ W (+ b). Weight layout (in, out) — matches the reference."""
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


def embedding(ids, weight, padding_idx=None):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


# ---- dropout ---------------------------------------------------------------

def dropout(x, p=0.5, training=True, mode="upscale_in_train", rng_name="dropout"):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            return x * (1.0 - p)  # reference contract: infer scales by (1-p)
        return x
    key = _rng.next_rng_key(rng_name)
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


# ---- normalization ---------------------------------------------------------

def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(x.ndim - len(tuple(normalized_shape)
                 if not isinstance(normalized_shape, int) else (normalized_shape,)), x.ndim))
    cdt = jnp.promote_types(x.dtype, jnp.float32)  # bf16→f32, f64 stays f64
    mean = jnp.mean(x.astype(cdt), axis=axes, keepdims=True)
    var = jnp.var(x.astype(cdt), axis=axes, keepdims=True)
    y = (x.astype(cdt) - mean) * lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


def rms_norm(x, weight=None, epsilon=1e-6):
    from paddle_tpu.ops import rms_norm as _rms
    return _rms.rms_norm(x, weight, epsilon)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    ch_axis = 1 if data_format == "NCHW" else -1
    axes = tuple(i for i in range(x.ndim) if i != (ch_axis % x.ndim))
    if training:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        new_rm = momentum * running_mean + (1 - momentum) * mean
        new_rv = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    shape = [1] * x.ndim
    shape[ch_axis % x.ndim] = x.shape[ch_axis % x.ndim]
    y = (x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y, new_rm, new_rv


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    g = x.reshape(n, num_groups, c // num_groups, *spatial)
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    g = (g - mean) * lax.rsqrt(var + epsilon)
    y = g.reshape(n, c, *spatial)
    if weight is not None:
        shape = (1, c) + (1,) * len(spatial)
        y = y * weight.reshape(shape)
        if bias is not None:
            y = y + bias.reshape(shape)
    if data_format == "NHWC":
        y = jnp.moveaxis(y, 1, -1)
    return y


# ---- conv / pool -----------------------------------------------------------

def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """weight layout: (out_ch, in_ch/groups, kh, kw) — reference layout."""
    stride, dilation = _pair(stride), _pair(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    elif isinstance(padding, (tuple, list)) and padding and \
            isinstance(padding[0], (tuple, list)):
        pad = [tuple(p) for p in padding]
    else:
        p = _pair(padding)
        pad = [(p[0], p[0]), (p[1], p[1])]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"))
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None)
    y = y.astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        y = y + bias.reshape(shape)
    return y


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    # lift (N,C,L) → (N,C,L,1); pad only the L axis
    pad = padding if isinstance(padding, str) else ((padding, padding), (0, 0))
    y = conv2d(x[..., None], weight[..., None], None, (stride, 1), pad,
               (dilation, 1), groups)[..., 0]
    if bias is not None:
        y = y + bias.reshape(1, -1, 1)
    return y


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, data_format="NCHW"):
    """weight layout: (in_ch, out_ch, kh, kw) — reference layout."""
    stride = _pair(stride)
    p = _pair(padding)
    op = _pair(output_padding)
    kh, kw = weight.shape[2], weight.shape[3]
    # out = (in-1)*stride - 2*pad + k + output_padding: extra rows go on the
    # high side of the dilated input
    pad = [(kh - 1 - p[0], kh - 1 - p[0] + op[0]),
           (kw - 1 - p[1], kw - 1 - p[1] + op[1])]
    dn = lax.conv_dimension_numbers(
        x.shape, (weight.shape[1], weight.shape[0], kh, kw),
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"))
    w = jnp.flip(jnp.swapaxes(weight, 0, 1), axis=(2, 3))
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad, lhs_dilation=stride,
        dimension_numbers=dn)
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        y = y + bias.reshape(shape)
    return y


def max_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    k, s = _pair(kernel_size), _pair(stride or kernel_size)
    p = _pair(padding)
    if data_format == "NCHW":
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    k, s = _pair(kernel_size), _pair(stride or kernel_size)
    p = _pair(padding)
    if data_format == "NCHW":
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window, strides, pads)
    return summed / counts


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    out = _pair(output_size)
    if data_format == "NCHW":
        h, w = x.shape[2], x.shape[3]
    else:
        h, w = x.shape[1], x.shape[2]
    assert h % out[0] == 0 and w % out[1] == 0, "adaptive pool needs divisible sizes"
    return avg_pool2d(x, (h // out[0], w // out[1]), (h // out[0], w // out[1]),
                      0, data_format)


def interpolate(x, scale_factor=None, size=None, mode="nearest",
                data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
    else:
        n, h, w, c = x.shape
    if size is None:
        sf = _pair(scale_factor)
        size = (int(h * sf[0]), int(w * sf[1]))
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]
    if data_format == "NCHW":
        y = jax.image.resize(x, (n, c, size[0], size[1]), method=method)
    else:
        y = jax.image.resize(x, (n, size[0], size[1], c), method=method)
    return y.astype(x.dtype)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    """`pad` is paddle-style: flat list, last dim first pairs for NCHW 4-tuples."""
    if len(pad) == x.ndim * 2:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # pad applies to trailing spatial dims, reference order (left,right,top,bottom)
        cfg = [(0, 0)] * x.ndim
        n_spatial = len(pad) // 2
        for i in range(n_spatial):
            axis = x.ndim - 1 - i
            cfg[axis] = (pad[2 * i], pad[2 * i + 1])
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    return jnp.pad(x, cfg, mode={"reflect": "reflect", "replicate": "edge"}[mode])


# ---- losses ----------------------------------------------------------------

@jax.custom_vjp
def _token_nll(logits, label):
    """-log softmax(logits)[label] over the LAST axis, per token.

    Memory-lean at LM scale: residuals are the ORIGINAL-dtype logits plus
    the (…,) fp32 lse — autodiff of log_softmax/logsumexp instead keeps a
    full-vocab fp32 tensor alive ((b, s, V) ≈ 1 GiB at V=32k b4 s2048).
    The backward's softmax-minus-onehot is one elementwise fusion emitting
    grads in the logits dtype."""
    return _token_nll_fwd(logits, label)[0]


def _token_nll_fwd(logits, label):
    cdt = jnp.promote_types(logits.dtype, jnp.float32)
    # both consumers of the fp32 cast are REDUCTIONS, so XLA fuses the
    # cast into their loops instead of materializing a full-vocab fp32
    # tensor; `picked` is a one-hot masked sum rather than a gather (a
    # gather on the class axis trips the SPMD partitioner when the logits
    # are vocab-sharded — ParallelCrossEntropy's mp path)
    lse = jax.scipy.special.logsumexp(logits.astype(cdt), axis=-1)
    oh = (jnp.arange(logits.shape[-1], dtype=label.dtype)
          == label[..., None])
    picked = jnp.sum(jnp.where(oh, logits.astype(cdt), 0), axis=-1)
    return lse - picked, (logits, label, lse)


def _token_nll_bwd(res, g):
    logits, label, lse = res
    cdt = jnp.promote_types(logits.dtype, jnp.float32)
    p = jnp.exp(logits.astype(cdt) - lse[..., None])
    oh = (jnp.arange(logits.shape[-1], dtype=label.dtype)
          == label[..., None])
    dz = (p - oh) * g[..., None]
    return dz.astype(logits.dtype), None


_token_nll.defvjp(_token_nll_fwd, _token_nll_bwd)


def cross_entropy(logits, label, reduction="mean", soft_label=False,
                  ignore_index=-100, axis=-1, label_smoothing=0.0):
    cdt = jnp.promote_types(logits.dtype, jnp.float32)
    if soft_label:
        logp = jax.nn.log_softmax(logits.astype(cdt), axis=axis)
        loss = -jnp.sum(label * logp, axis=axis)
    else:
        label = label.astype(jnp.int32)
        ax = axis % logits.ndim
        # reference softmax_with_cross_entropy convention: hard labels may
        # carry a singleton at the class axis; the loss keeps that dim
        keep_axis = label.ndim == logits.ndim and label.shape[ax] == 1
        if keep_axis:
            label = jnp.squeeze(label, ax)
        if label_smoothing > 0.0:
            z = logits.astype(cdt)
            lse = jax.scipy.special.logsumexp(z, axis=ax)
            oh = (jax.lax.broadcasted_iota(label.dtype, z.shape, ax)
                  == jnp.expand_dims(label, ax))
            picked = jnp.sum(jnp.where(oh, z, 0), axis=ax)
            # -sum(oh·logp), oh = (1-ls)·onehot + ls/n
            n = z.shape[ax]
            mean_nll = lse - jnp.sum(z, axis=ax) / n
            loss = ((1.0 - label_smoothing) * (lse - picked)
                    + label_smoothing * mean_nll)
        else:
            z = logits if ax == logits.ndim - 1 else jnp.moveaxis(
                logits, ax, -1)
            loss = _token_nll(z, label)
        valid = (label != ignore_index)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
        if keep_axis:
            loss = jnp.expand_dims(loss, ax)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1):
    return cross_entropy(logits, label, reduction="none", soft_label=soft_label,
                         axis=axis)


def mse_loss(input, label, reduction="mean"):
    loss = jnp.square(input - label)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def l1_loss(input, label, reduction="mean"):
    loss = jnp.abs(input - label)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def binary_cross_entropy_with_logits(logit, label, reduction="mean"):
    loss = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def nll_loss(log_probs, label, reduction="mean"):
    picked = jnp.take_along_axis(log_probs, label[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    loss = -picked
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def kl_div(input, label, reduction="mean"):
    loss = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction in ("sum", "batchmean"):
        s = jnp.sum(loss)
        return s / input.shape[0] if reduction == "batchmean" else s
    return loss


# ---- attention -------------------------------------------------------------

def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None,
                                 kv_lens=None, segment_ids=None,
                                 kv_segment_ids=None, window_size=None,
                                 alibi_slopes=None):
    """q/k/v: (batch, seq, heads, head_dim) — the reference's layout.

    Dispatches to the Pallas flash kernel on TPU when profitable
    (paddle_tpu.ops.flash_attention), else the XLA softmax path. Supports
    cross-attention (sq != sk) and the structured-mask extensions
    `kv_lens` / `segment_ids` / `window_size` / `alibi_slopes` (see
    ops.flash_attention).

    Float `attn_mask` entries ≤ −1e9 mean "fully masked" on the Pallas
    path (whole blocks below the threshold are skipped); keep finite soft
    penalties well above −1e9 or the Pallas and XLA paths diverge — see
    ops.flash_attention.scaled_dot_product_attention for details.
    """
    from paddle_tpu.ops import flash_attention as fa
    return fa.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=dropout_p, is_causal=is_causal,
        training=training, scale=scale, kv_lens=kv_lens,
        segment_ids=segment_ids, kv_segment_ids=kv_segment_ids,
        window_size=window_size, alibi_slopes=alibi_slopes)


# ---- misc ------------------------------------------------------------------

def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


def label_smooth(label, epsilon=0.1):
    n = label.shape[-1]
    return label * (1 - epsilon) + epsilon / n


def normalize(x, p=2, axis=1, epsilon=1e-12):
    denom = jnp.maximum(jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True), epsilon)
    return x / denom


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot_ = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot_ / jnp.maximum(n1 * n2, eps)


# ---- activation breadth (reference: python/paddle/nn/functional/activation.py)

def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def celu(x, alpha=1.0):
    return jnp.maximum(x, 0.0) + jnp.minimum(
        0.0, alpha * jnp.expm1(x / alpha))


def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold,
                               jnp.zeros_like(x)))


def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, jnp.zeros_like(x))


def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


def tanhshrink(x):
    return x - jnp.tanh(x)


def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, jnp.zeros_like(x))


def softsign(x):
    return x / (1.0 + jnp.abs(x))


def prelu(x, weight):
    """weight: scalar or per-channel (dim 1) negative-slope parameter."""
    w = weight
    if w.ndim == 1 and x.ndim > 1 and w.shape[0] > 1:
        w = w.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x >= 0, x, w * x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False):
    """Randomized leaky ReLU; eval uses the mean slope (reference parity)."""
    if training:
        from paddle_tpu.core import rng as _rng_mod
        key = _rng_mod.next_rng_key("rrelu")
        slope = jax.random.uniform(key, x.shape, minval=lower, maxval=upper)
    else:
        slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


def maxout(x, groups, axis=1):
    c = x.shape[axis]
    assert c % groups == 0, f"channels {c} not divisible by groups {groups}"
    new_shape = (x.shape[:axis] + (c // groups, groups) +
                 x.shape[axis + 1:])
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from paddle_tpu.core import rng as _rng_mod
    key = _rng_mod.next_rng_key("gumbel")
    g = jax.random.gumbel(key, x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:  # straight-through: one-hot forward, soft gradient
        idx = jnp.argmax(y, axis=axis)
        hard_y = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
        y = jax.lax.stop_gradient(hard_y - y) + y
    return y


# ---- loss breadth (reference: python/paddle/nn/functional/loss.py) ---------

def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(loss, reduction)


def huber_loss(input, label, reduction="mean", delta=1.0):
    d = jnp.abs(input - label)
    loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.maximum(0.0, -label * (input - other) + margin)
    return _reduce_loss(loss, reduction)


def soft_margin_loss(input, label, reduction="mean"):
    # softplus form: log(1 + exp(z)) without overflow at large |z|
    loss = jax.nn.softplus(-label * input)
    return _reduce_loss(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean"):
    loss = -(label * jax.nn.log_sigmoid(input) +
             (1.0 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = jnp.mean(loss, axis=-1)
    return _reduce_loss(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p),
                                 axis=-1), 1.0 / p)

    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.maximum(0.0, d_pos - d_neg + margin)
    return _reduce_loss(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    sim = cosine_similarity(input1, input2, axis=-1)
    loss = jnp.where(label > 0, 1.0 - sim, jnp.maximum(0.0, sim - margin))
    return _reduce_loss(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label > 0, input, jnp.maximum(0.0, margin - input))
    return _reduce_loss(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        # Stirling approximation for label! (label > 1)
        stirling = (label * jnp.log(label) - label +
                    0.5 * jnp.log(2.0 * jnp.pi * label))
        loss = loss + jnp.where(label > 1, stirling, jnp.zeros_like(label))
    return _reduce_loss(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         epsilon=1e-12):
    p = jnp.clip(input, epsilon, 1.0 - epsilon)
    loss = -(label * jnp.log(p) + (1.0 - label) * jnp.log1p(-p))
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification (reference: warpctc kernel,
    paddle.nn.functional.ctc_loss).

    log_probs: (T, B, C) raw logits — log_softmax is applied internally,
    matching the reference contract (warpctc softmaxes internally).
    Passing already-log-softmaxed inputs is also fine: log_softmax is
    idempotent. labels: (B, L) int32 padded; input_lengths (B,),
    label_lengths (B,). Forward DP in the log semiring runs as one
    lax.scan over time — static shapes, TPU-friendly.

    reduction='mean' divides each sequence's loss by its label length
    before averaging (reference/torch semantics).
    """
    log_probs = jax.nn.log_softmax(log_probs, axis=-1)
    T, B, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    neg_inf = jnp.asarray(-1e30, log_probs.dtype)

    # extended label sequence: blank, l1, blank, l2, ... blank
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
    pos = jnp.arange(S)[None, :]

    # transitions: from s, s-1 always; from s-2 iff ext[s] != blank and
    # ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (ext != blank) & (ext != ext_m2)

    def emit(t_logp, a):       # a: (B, S) alphas
        return jnp.take_along_axis(t_logp, ext, axis=-1) + a

    a0 = jnp.full((B, S), neg_inf)
    a0 = a0.at[:, 0].set(log_probs[0, jnp.arange(B), ext[:, 0]])
    valid1 = (label_lengths > 0)
    a0 = a0.at[:, 1].set(jnp.where(
        valid1, log_probs[0, jnp.arange(B), ext[:, 1]], neg_inf))

    def step(a, t_logp):
        a_m1 = jnp.pad(a, ((0, 0), (1, 0)), constant_values=neg_inf)[:, :S]
        a_m2 = jnp.pad(a, ((0, 0), (2, 0)), constant_values=neg_inf)[:, :S]
        a_m2 = jnp.where(can_skip, a_m2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(a, a_m1), a_m2)
        return emit(t_logp, merged), merged

    def scan_step(carry, xs):
        t_idx, t_logp = xs
        a = carry
        new_a, _ = step(a, t_logp)
        # freeze alphas past each sequence's input length
        new_a = jnp.where((t_idx < input_lengths)[:, None], new_a, a)
        return new_a, None

    alphas, _ = jax.lax.scan(
        scan_step, a0, (jnp.arange(1, T), log_probs[1:]))

    end = 2 * label_lengths          # blank after last label
    end_m1 = jnp.maximum(end - 1, 0)  # last label
    ll_blank = jnp.take_along_axis(alphas, end[:, None], axis=1)[:, 0]
    ll_label = jnp.take_along_axis(alphas, end_m1[:, None], axis=1)[:, 0]
    # empty-label rows have only the all-blank path — don't count it twice
    ll_label = jnp.where(label_lengths > 0, ll_label, neg_inf)
    ll = jnp.logaddexp(ll_blank, ll_label)
    loss = -ll
    if norm_by_times:
        loss = loss / input_lengths.astype(loss.dtype)
    if reduction == "mean":
        denom = jnp.maximum(label_lengths, 1).astype(loss.dtype)
        return jnp.mean(loss / denom)
    return _reduce_loss(loss, reduction)


# ---- misc breadth -----------------------------------------------------------

def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c, h, w = x.shape
    oc = c // (r * r)
    x = x.reshape(n, oc, r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    x = x.reshape(n, oc, h * r, w * r)
    if data_format == "NHWC":
        x = jnp.moveaxis(x, 1, -1)
    return x


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = x.transpose(0, 1, 3, 5, 2, 4)
    x = x.reshape(n, c * r * r, h // r, w // r)
    if data_format == "NHWC":
        x = jnp.moveaxis(x, 1, -1)
    return x


def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = x.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
    if data_format == "NHWC":
        x = jnp.moveaxis(x, 1, -1)
    return x


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference unfold): (N, C, H, W) → (N, C·kh·kw, L)."""
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, ckk, oh, ow = patches.shape
    return patches.reshape(n, ckk, oh * ow)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im: inverse of unfold by scatter-add."""
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    nh = (oh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    nw = (ow + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = x.reshape(n, c, kh, kw, nh, nw)
    out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            hi = i * dh
            wj = j * dw
            out = out.at[:, :, hi:hi + nh * sh:sh,
                         wj:wj + nw * sw:sw].add(cols[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


def instance_norm(x, weight=None, bias=None, epsilon=1e-5,
                  data_format="NCHW"):
    ch_axis = 1 if data_format == "NCHW" else -1
    axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else \
        tuple(i for i in range(1, x.ndim - 1))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + epsilon)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    ch_axis = 1 if data_format == "NCHW" else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    pad_cfg = [(0, 0)] * x.ndim
    pad_cfg[ch_axis] = (half, size - 1 - half)
    sq = jnp.pad(sq, pad_cfg)
    win = sum(jax.lax.slice_in_dim(sq, i, i + x.shape[ch_axis], axis=ch_axis)
              for i in range(size))
    return x / jnp.power(k + alpha * win / size, beta)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    d = jnp.abs(x - y) + epsilon
    return jnp.power(jnp.sum(jnp.power(d, p), axis=-1), 1.0 / p) if not \
        keepdim else jnp.power(jnp.sum(jnp.power(d, p), axis=-1,
                                       keepdims=True), 1.0 / p)


# ---- 3-D / 1-D conv & pooling breadth --------------------------------------

def _ntuple(v, n):
    return tuple(v) if isinstance(v, (tuple, list)) else (v,) * n


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    """weight layout: (out_ch, in_ch/groups, kd, kh, kw)."""
    stride = _ntuple(stride, 3)
    dilation = _ntuple(dilation, 3)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _ntuple(padding, 3)
        pad = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW"
        else ("NDHWC", "OIDHW", "NDHWC"))
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.promote_types(x.dtype, jnp.float32)
        if x.dtype != jnp.bfloat16 else None)
    y = y.astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1, 1, 1) if data_format == "NCDHW" else (1, 1, 1, 1, -1)
        y = y + bias.reshape(shape)
    return y


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, data_format="NCDHW"):
    """weight layout: (in_ch, out_ch, kd, kh, kw)."""
    stride = _ntuple(stride, 3)
    p = _ntuple(padding, 3)
    op = _ntuple(output_padding, 3)
    k = weight.shape[2:]
    pad = [(k[i] - 1 - p[i], k[i] - 1 - p[i] + op[i]) for i in range(3)]
    w = jnp.flip(weight, axis=(2, 3, 4))
    w = jnp.swapaxes(w, 0, 1)       # (out, in, ...)
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, ("NCDHW", "OIDHW", "NCDHW"))
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1, 1), padding=pad, lhs_dilation=stride,
        dimension_numbers=dn)
    y = y.astype(x.dtype)
    if bias is not None:
        y = y + bias.reshape(1, -1, 1, 1, 1)
    return y


def _pool(x, kernel, stride, padding, nd, reducer, init_val, avg=False,
          ceil_mode=False):
    kernel = _ntuple(kernel, nd)
    stride = _ntuple(stride if stride is not None else kernel, nd)
    p = _ntuple(padding, nd)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = [(0, 0), (0, 0)]
    for i, (ki, si, pi) in enumerate(zip(kernel, stride, p)):
        hi = pi
        if ceil_mode:
            # last partial window counts, but no window may START in the
            # right padding (reference pooling rule) — compute the exact
            # output count and the (possibly negative) high pad for it
            n = x.shape[2 + i]
            out = -(-(n + 2 * pi - ki) // si) + 1
            if (out - 1) * si >= n + pi:
                out -= 1
            hi = (out - 1) * si + ki - n - pi
        pads.append((pi, hi))
    y = lax.reduce_window(x, init_val, reducer, window, strides, pads)
    if avg:
        # divide by the REAL element count per window (padding excluded —
        # reference exclusive=True semantics)
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        y = y / counts
    return y


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    return _pool(x, kernel_size, stride, padding, 1, lax.max, -jnp.inf,
                 ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    return _pool(x, kernel_size, stride, padding, 1, lax.add, 0.0, avg=True,
                 ceil_mode=ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    return _pool(x, kernel_size, stride, padding, 3, lax.max, -jnp.inf,
                 ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    return _pool(x, kernel_size, stride, padding, 3, lax.add, 0.0, avg=True,
                 ceil_mode=ceil_mode)


def adaptive_max_pool2d(x, output_size):
    oh, ow = _pair(output_size)
    n, c, h, w = x.shape
    assert h % oh == 0 and w % ow == 0, \
        f"adaptive pool needs divisible sizes, got {(h, w)} -> {(oh, ow)}"
    return jnp.max(x.reshape(n, c, oh, h // oh, ow, w // ow), axis=(3, 5))


def adaptive_avg_pool1d(x, output_size):
    n, c, l = x.shape
    o = output_size if isinstance(output_size, int) else output_size[0]
    assert l % o == 0
    return jnp.mean(x.reshape(n, c, o, l // o), axis=-1)


def adaptive_avg_pool3d(x, output_size):
    od, oh, ow = _ntuple(output_size, 3)
    n, c, d, h, w = x.shape
    assert d % od == 0 and h % oh == 0 and w % ow == 0
    return jnp.mean(
        x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow),
        axis=(3, 5, 7))


# reference path: paddle.nn.functional.flash_attention.flash_attention
from paddle_tpu.ops.flash_attention import flash_attention  # noqa: F401,E402


# ---- long-tail functional parity (reference python/paddle/nn/functional) ---

def square_error_cost(input, label):
    return jnp.square(input - label)


def log_loss(input, label, epsilon=1e-4):
    return (-label * jnp.log(input + epsilon)
            - (1.0 - label) * jnp.log(1.0 - input + epsilon))


def sequence_mask(x, maxlen=None, dtype="int64"):
    """(..., n) lengths → (..., n, maxlen) 0/1 mask.

    With ``maxlen=None`` the mask width is inferred as ``max(x)``, which
    needs a concrete value — inside jit/grad/scan pass ``maxlen`` explicitly
    (XLA requires static shapes).
    """
    from paddle_tpu.core.dtype import to_jax_dtype
    x = jnp.asarray(x)
    if maxlen is None:
        if isinstance(x, jax.core.Tracer):
            raise ValueError(
                "sequence_mask(maxlen=None) infers the mask width from "
                "max(x), which is unavailable under jit/grad/scan tracing "
                "(the output shape would be data-dependent). Pass an "
                "explicit static maxlen.")
        m = int(jnp.max(x))
    else:
        m = int(maxlen)
    return (jnp.arange(m) < x[..., None]).astype(to_jax_dtype(dtype))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum"):
    p = jax.nn.sigmoid(logit.astype(jnp.float32))
    lab = label.astype(jnp.float32)
    ce = (jnp.maximum(logit, 0) - logit * lab
          + jnp.log1p(jnp.exp(-jnp.abs(logit)))).astype(jnp.float32)
    p_t = p * lab + (1.0 - p) * (1.0 - lab)
    a_t = alpha * lab + (1.0 - alpha) * (1.0 - lab)
    loss = a_t * ((1.0 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce_loss(loss, reduction)   # shared helper (loss section)


def dice_loss(input, label, epsilon=1e-5):
    """input (N, ..., C) probabilities, label (N, ..., 1) int classes."""
    c = input.shape[-1]
    oh = jax.nn.one_hot(jnp.squeeze(label, -1), c, dtype=input.dtype)
    red = tuple(range(1, input.ndim))
    inter = jnp.sum(input * oh, axis=red)
    union = jnp.sum(input, axis=red) + jnp.sum(oh, axis=red)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Reference paddle npair_loss: softmax CE over anchor·positiveᵀ with
    same-label targets + L2 on the embeddings."""
    a = anchor.astype(jnp.float32)
    p = positive.astype(jnp.float32)
    labels = labels.reshape(-1)
    sim = jnp.matmul(a, p.T,
                     preferred_element_type=jnp.float32)   # (n, n)
    tgt = (labels[:, None] == labels[None, :]).astype(jnp.float32)
    tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
    # Beta = 0.25 — the reference's (and TF's) npair regularizer weight
    reg = 0.25 * l2_reg * (jnp.mean(jnp.sum(a * a, 1)) +
                           jnp.mean(jnp.sum(p * p, 1)))
    return ce + reg


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    var = jnp.maximum(variance.astype(jnp.float32), epsilon)
    loss = 0.5 * (jnp.log(var)
                  + jnp.square(input - label).astype(jnp.float32) / var)
    if full:
        loss = loss + 0.5 * math.log(2 * math.pi)
    return _reduce_loss(loss, reduction)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """TSM channel shift across the segment (time) axis."""
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.pad(xr[:, 1:, :fold], ((0, 0), (0, 1), (0, 0), (0, 0),
                                      (0, 0)))
    right = jnp.pad(xr[:, :-1, fold:2 * fold],
                    ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
    out = jnp.concatenate([left, right, xr[:, :, 2 * fold:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest",
             data_format="NCHW"):
    return interpolate(x, scale_factor=scale_factor, size=size, mode=mode,
                       data_format=data_format)


def zeropad2d(x, padding, data_format="NCHW"):
    from paddle_tpu import nn as _nn
    return _nn.ZeroPad2D(padding, data_format=data_format)(x)


def alpha_dropout(x, p=0.5, training=True):
    from paddle_tpu import nn as _nn
    layer = _nn.AlphaDropout(p)
    layer.training = training
    return layer(x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    from paddle_tpu import nn as _nn
    layer = _nn.Dropout2D(p, data_format=data_format)
    layer.training = training
    return layer(x)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    from paddle_tpu import nn as _nn
    layer = _nn.Dropout3D(p, data_format=data_format)
    layer.training = training
    return layer(x)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None):
    from paddle_tpu import nn as _nn
    return _nn.MaxUnPool1D(kernel_size, stride, padding, data_format,
                           output_size)(x, indices)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None):
    from paddle_tpu import nn as _nn
    return _nn.MaxUnPool2D(kernel_size, stride, padding, data_format,
                           output_size)(x, indices)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None):
    from paddle_tpu import nn as _nn
    return _nn.MaxUnPool3D(kernel_size, stride, padding, data_format,
                           output_size)(x, indices)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL"):
    from paddle_tpu import nn as _nn
    return _nn.LPPool1D(norm_type, kernel_size, stride, padding, ceil_mode,
                        data_format)(x)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW"):
    from paddle_tpu import nn as _nn
    return _nn.LPPool2D(norm_type, kernel_size, stride, padding, ceil_mode,
                        data_format)(x)


def bilinear(x1, x2, weight, bias=None):
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2,
                     preferred_element_type=jnp.float32).astype(x1.dtype)
    return out + bias if bias is not None else out


def affine_grid(theta, out_shape, align_corners=True):
    """theta (N, 2, 3) → sampling grid (N, H, W, 2) in [-1, 1] coords."""
    n, _, h, w = (out_shape if len(out_shape) == 4
                  else (out_shape[0], 1, out_shape[1], out_shape[2]))

    def base(steps):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, steps)
        half = 1.0 - 1.0 / steps
        return jnp.linspace(-half, half, steps)

    ys = base(h)
    xs = base(w)
    ones = jnp.ones((h, w))
    grid = jnp.stack([jnp.broadcast_to(xs[None, :], (h, w)),
                      jnp.broadcast_to(ys[:, None], (h, w)), ones],
                     axis=-1)                       # (H, W, 3)
    theta = jnp.asarray(theta, jnp.float32)
    # fp32 accumulation: default TPU matmul precision (bf16 passes) puts
    # ~1e-2 error on the [-1, 1] grid coords ≈ pixels at high resolution
    return jnp.einsum("hwk,nok->nhwo", grid, theta,
                      preferred_element_type=jnp.float32)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """4-D grid sampling (reference paddle.nn.functional.grid_sample):
    x (N, C, H, W), grid (N, Hg, Wg, 2) with xy in [-1, 1]."""
    n, c, h, w = x.shape
    gx = grid[..., 0].astype(jnp.float32)
    gy = grid[..., 1].astype(jnp.float32)

    def unnorm(g, size):
        if align_corners:
            return (g + 1.0) / 2.0 * (size - 1)
        return ((g + 1.0) * size - 1.0) / 2.0

    fx = unnorm(gx, w)
    fy = unnorm(gy, h)

    def reflect(v, lo, hi):
        # reflect into [lo, hi] (continuous coordinates, period 2*(hi-lo))
        rng_ = hi - lo
        v = jnp.abs(v - lo) % (2 * rng_)
        return lo + jnp.where(v > rng_, 2 * rng_ - v, v)

    if padding_mode == "border":
        fx = jnp.clip(fx, 0, w - 1)
        fy = jnp.clip(fy, 0, h - 1)
    elif padding_mode == "reflection":
        if align_corners:
            fx = reflect(fx, 0.0, w - 1.0)
            fy = reflect(fy, 0.0, h - 1.0)
        else:
            fx = jnp.clip(reflect(fx, -0.5, w - 0.5), 0, w - 1)
            fy = jnp.clip(reflect(fy, -0.5, h - 0.5), 0, h - 1)

    def gather(ix, iy):
        valid = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        vals = x[jnp.arange(n)[:, None, None], :, iyc, ixc]  # (N,Hg,Wg,C)
        return jnp.where(valid[..., None], vals, 0.0)

    if mode == "nearest":
        out = gather(jnp.round(fx).astype(jnp.int32),
                     jnp.round(fy).astype(jnp.int32))
    else:
        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = fx - x0
        wy = fy - y0
        out = (gather(x0, y0) * ((1 - wx) * (1 - wy))[..., None]
               + gather(x1, y0) * (wx * (1 - wy))[..., None]
               + gather(x0, y1) * ((1 - wx) * wy)[..., None]
               + gather(x1, y1) * (wx * wy)[..., None])
    return jnp.transpose(out, (0, 3, 1, 2)).astype(x.dtype)  # (N,C,Hg,Wg)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, return_softmax=False,
                         reduction="mean"):
    """ArcFace-family margins: target logit cosθ → cos(m1·θ + m2) − m3,
    all logits scaled by `scale`, then softmax CE."""
    # clip strictly inside (−1, 1): arccos has infinite slope at the
    # endpoints, and normalized embeddings routinely hit cos == ±1.0 —
    # the gradient would be NaN and poison the whole step
    eps = 1e-6
    cos = jnp.clip(logits.astype(jnp.float32), -1.0 + eps, 1.0 - eps)
    theta = jnp.arccos(cos)
    tgt = jnp.cos(margin1 * theta + margin2) - margin3
    oh = jax.nn.one_hot(label.reshape(-1), logits.shape[-1],
                        dtype=jnp.float32)
    adjusted = scale * jnp.where(oh > 0, tgt, cos)
    loss = cross_entropy(adjusted, label.reshape(-1), reduction=reduction)
    if return_softmax:
        return loss, jax.nn.softmax(adjusted, axis=-1)
    return loss


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None):
    """Functional form of nn.AdaptiveLogSoftmaxWithLoss (same math, params
    passed explicitly). Returns (per-sample logprob of the target, mean
    NLL loss)."""
    n_clusters = len(tail_weights)
    head_logits = input @ head_weight
    if head_bias is not None:
        head_logits = head_logits + head_bias
    head_logp = jax.nn.log_softmax(head_logits, axis=-1)
    shortlist = cutoffs[0]
    out = jnp.zeros(input.shape[0], jnp.float32)
    in_short = label < shortlist
    idx_short = jnp.clip(label, 0, shortlist - 1)
    out = jnp.where(
        in_short,
        jnp.take_along_axis(head_logp, idx_short[:, None], 1)[:, 0], out)
    for ci in range(n_clusters):
        lo = cutoffs[ci]
        hi = cutoffs[ci + 1]
        in_c = (label >= lo) & (label < hi)
        w1, w2 = tail_weights[ci]
        tail_logp = jax.nn.log_softmax((input @ w1) @ w2, axis=-1)
        rel = jnp.clip(label - lo, 0, hi - lo - 1)
        lp = (head_logp[:, shortlist + ci]
              + jnp.take_along_axis(tail_logp, rel[:, None], 1)[:, 0])
        out = jnp.where(in_c, lp, out)
    return out, -jnp.mean(out)


def rnnt_loss(logits, labels, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean"):
    """RNN-Transducer loss (reference paddle.nn.functional.rnnt_loss over
    the warprnnt kernel; canonical python/paddle/nn/functional/loss.py).

    logits (B, T, U+1, V) UNNORMALIZED joint-network outputs; labels
    (B, U) int; input_lengths (B,), label_lengths (B,). Forward DP in the
    log semiring: alpha[t,u] = logaddexp(alpha[t-1,u] + blank[t-1,u],
    alpha[t,u-1] + emit[t,u-1]). TPU-native shape: ONE lax.scan over T
    whose inner u-recurrence (a first-order log-semiring linear
    recurrence) is solved with lax.associative_scan — O(T) sequential
    steps, O(log U) inner depth, no host loop. Gradients via jax.grad are
    the exact RNNT gradients (the warprnnt backward computes the same
    quantity analytically).

    fastemit_lambda shapes the GRADIENT in the reference kernel (FastEmit
    regularization); only 0.0 is supported here — autodiff supplies the
    exact lambda=0 gradient. (STATUS.md EXCLUSIONS.)
    """
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: fastemit_lambda != 0 reshapes the backward pass "
            "inside the reference's warprnnt kernel; the autodiff "
            "gradient here is the exact fastemit_lambda=0 one")
    neg = -1e30
    lp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
    labels = jnp.asarray(labels).astype(jnp.int32)
    input_lengths = jnp.asarray(input_lengths, jnp.int32)
    label_lengths = jnp.asarray(label_lengths, jnp.int32)
    B, T, U1, V = lp.shape
    blank_lp = lp[..., blank]                               # (B, T, U+1)
    emit = jnp.take_along_axis(
        lp[:, :, :U1 - 1, :], labels[:, None, :, None], axis=-1)[..., 0]
    emit = jnp.pad(emit, ((0, 0), (0, 0), (0, 1)), constant_values=neg)

    def assoc(e1, e2):
        # element u encodes x_u = logaddexp(x_{u-1} + a_u, b_u)
        a1, b1 = e1
        a2, b2 = e2
        return a1 + a2, jnp.logaddexp(b1 + a2, b2)

    def solve_row(a_coef, b_vals):
        _, row = jax.lax.associative_scan((lambda x, y: assoc(x, y)),
                                          (a_coef, b_vals), axis=1)
        return row

    shift = lambda em: jnp.pad(em[:, :-1], ((0, 0), (1, 0)),
                               constant_values=neg)
    # t = 0: alpha[0,u] = cumsum of emit[0, :u]
    b0 = jnp.full((B, U1), neg).at[:, 0].set(0.0)
    row0 = solve_row(shift(emit[:, 0]), b0)

    def step(prev_row, xs):
        bl_prev, em_t = xs                                  # (B, U+1) each
        from_top = prev_row + bl_prev
        row = solve_row(shift(em_t), from_top)
        return row, row

    xs = (jnp.moveaxis(blank_lp, 1, 0)[:-1],                # blank[t-1]
          jnp.moveaxis(emit, 1, 0)[1:])                     # emit[t]
    _, rows = jax.lax.scan(step, row0, xs)                  # (T-1, B, U+1)
    alphas = jnp.concatenate([row0[None], rows], axis=0)    # (T, B, U+1)
    tb = jnp.clip(input_lengths - 1, 0, T - 1)
    ub = jnp.clip(label_lengths, 0, U1 - 1)
    bi = jnp.arange(B)
    ll = alphas[tb, bi, ub] + blank_lp[bi, tb, ub]
    loss = -ll
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss
