"""Functional NN ops (`paddle.nn.functional` parity).

Ref: python/paddle/nn/functional/ — activations, linear, conv, pooling, norm,
loss, attention. Each op is a jnp/lax composition that XLA fuses; the hot fused
paths (flash attention, rms_norm, rope) additionally have Pallas TPU kernels in
`paddle_tpu.ops`, which these wrappers dispatch to when profitable.
"""

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import rng as _rng


# ---- activations -----------------------------------------------------------

def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jnp.minimum(jax.nn.relu(x), 6.0)


def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def silu(x):
    return jax.nn.silu(x)


swish = silu


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def hardsigmoid(x):
    return jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def softplus(x, beta=1.0):
    return jax.nn.softplus(beta * x) / beta


def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


# ---- linear / embedding ----------------------------------------------------

def linear(x, weight, bias=None):
    """y = x @ W (+ b). Weight layout (in, out) — matches the reference."""
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


def embedding(ids, weight, padding_idx=None):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


# ---- dropout ---------------------------------------------------------------

def dropout(x, p=0.5, training=True, mode="upscale_in_train", rng_name="dropout"):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            return x * (1.0 - p)  # reference contract: infer scales by (1-p)
        return x
    key = _rng.next_rng_key(rng_name)
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


# ---- normalization ---------------------------------------------------------

def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(x.ndim - len(tuple(normalized_shape)
                 if not isinstance(normalized_shape, int) else (normalized_shape,)), x.ndim))
    mean = jnp.mean(x.astype(jnp.float32), axis=axes, keepdims=True)
    var = jnp.var(x.astype(jnp.float32), axis=axes, keepdims=True)
    y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


def rms_norm(x, weight=None, epsilon=1e-6):
    from paddle_tpu.ops import rms_norm as _rms
    return _rms.rms_norm(x, weight, epsilon)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    ch_axis = 1 if data_format == "NCHW" else -1
    axes = tuple(i for i in range(x.ndim) if i != (ch_axis % x.ndim))
    if training:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        new_rm = momentum * running_mean + (1 - momentum) * mean
        new_rv = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    shape = [1] * x.ndim
    shape[ch_axis % x.ndim] = x.shape[ch_axis % x.ndim]
    y = (x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y, new_rm, new_rv


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    g = x.reshape(n, num_groups, c // num_groups, *spatial)
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    g = (g - mean) * lax.rsqrt(var + epsilon)
    y = g.reshape(n, c, *spatial)
    if weight is not None:
        shape = (1, c) + (1,) * len(spatial)
        y = y * weight.reshape(shape)
        if bias is not None:
            y = y + bias.reshape(shape)
    if data_format == "NHWC":
        y = jnp.moveaxis(y, 1, -1)
    return y


# ---- conv / pool -----------------------------------------------------------

def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """weight layout: (out_ch, in_ch/groups, kh, kw) — reference layout."""
    stride, dilation = _pair(stride), _pair(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    elif isinstance(padding, (tuple, list)) and padding and \
            isinstance(padding[0], (tuple, list)):
        pad = [tuple(p) for p in padding]
    else:
        p = _pair(padding)
        pad = [(p[0], p[0]), (p[1], p[1])]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"))
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None)
    y = y.astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        y = y + bias.reshape(shape)
    return y


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    # lift (N,C,L) → (N,C,L,1); pad only the L axis
    pad = padding if isinstance(padding, str) else ((padding, padding), (0, 0))
    y = conv2d(x[..., None], weight[..., None], None, (stride, 1), pad,
               (dilation, 1), groups)[..., 0]
    if bias is not None:
        y = y + bias.reshape(1, -1, 1)
    return y


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, data_format="NCHW"):
    """weight layout: (in_ch, out_ch, kh, kw) — reference layout."""
    stride = _pair(stride)
    p = _pair(padding)
    op = _pair(output_padding)
    kh, kw = weight.shape[2], weight.shape[3]
    # out = (in-1)*stride - 2*pad + k + output_padding: extra rows go on the
    # high side of the dilated input
    pad = [(kh - 1 - p[0], kh - 1 - p[0] + op[0]),
           (kw - 1 - p[1], kw - 1 - p[1] + op[1])]
    dn = lax.conv_dimension_numbers(
        x.shape, (weight.shape[1], weight.shape[0], kh, kw),
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"))
    w = jnp.flip(jnp.swapaxes(weight, 0, 1), axis=(2, 3))
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad, lhs_dilation=stride,
        dimension_numbers=dn)
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        y = y + bias.reshape(shape)
    return y


def max_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    k, s = _pair(kernel_size), _pair(stride or kernel_size)
    p = _pair(padding)
    if data_format == "NCHW":
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    k, s = _pair(kernel_size), _pair(stride or kernel_size)
    p = _pair(padding)
    if data_format == "NCHW":
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window, strides, pads)
    return summed / counts


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    out = _pair(output_size)
    if data_format == "NCHW":
        h, w = x.shape[2], x.shape[3]
    else:
        h, w = x.shape[1], x.shape[2]
    assert h % out[0] == 0 and w % out[1] == 0, "adaptive pool needs divisible sizes"
    return avg_pool2d(x, (h // out[0], w // out[1]), (h // out[0], w // out[1]),
                      0, data_format)


def interpolate(x, scale_factor=None, size=None, mode="nearest",
                data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
    else:
        n, h, w, c = x.shape
    if size is None:
        sf = _pair(scale_factor)
        size = (int(h * sf[0]), int(w * sf[1]))
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]
    if data_format == "NCHW":
        y = jax.image.resize(x, (n, c, size[0], size[1]), method=method)
    else:
        y = jax.image.resize(x, (n, size[0], size[1], c), method=method)
    return y.astype(x.dtype)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    """`pad` is paddle-style: flat list, last dim first pairs for NCHW 4-tuples."""
    if len(pad) == x.ndim * 2:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # pad applies to trailing spatial dims, reference order (left,right,top,bottom)
        cfg = [(0, 0)] * x.ndim
        n_spatial = len(pad) // 2
        for i in range(n_spatial):
            axis = x.ndim - 1 - i
            cfg[axis] = (pad[2 * i], pad[2 * i + 1])
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    return jnp.pad(x, cfg, mode={"reflect": "reflect", "replicate": "edge"}[mode])


# ---- losses ----------------------------------------------------------------

def cross_entropy(logits, label, reduction="mean", soft_label=False,
                  ignore_index=-100, axis=-1, label_smoothing=0.0):
    logits_f32 = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits_f32, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
    else:
        label = label.astype(jnp.int32)
        oh = jax.nn.one_hot(label, logits.shape[axis], axis=axis, dtype=jnp.float32)
        if label_smoothing > 0.0:
            n = logits.shape[axis]
            oh = oh * (1.0 - label_smoothing) + label_smoothing / n
        loss = -jnp.sum(oh * logp, axis=axis)
        valid = (label != ignore_index)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1):
    return cross_entropy(logits, label, reduction="none", soft_label=soft_label,
                         axis=axis)


def mse_loss(input, label, reduction="mean"):
    loss = jnp.square(input - label)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def l1_loss(input, label, reduction="mean"):
    loss = jnp.abs(input - label)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def binary_cross_entropy_with_logits(logit, label, reduction="mean"):
    loss = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def nll_loss(log_probs, label, reduction="mean"):
    picked = jnp.take_along_axis(log_probs, label[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    loss = -picked
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def kl_div(input, label, reduction="mean"):
    loss = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction in ("sum", "batchmean"):
        s = jnp.sum(loss)
        return s / input.shape[0] if reduction == "batchmean" else s
    return loss


# ---- attention -------------------------------------------------------------

def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None):
    """q/k/v: (batch, seq, heads, head_dim) — the reference's layout.

    Dispatches to the Pallas flash kernel on TPU when profitable
    (paddle_tpu.ops.flash_attention), else the XLA softmax path.
    """
    from paddle_tpu.ops import flash_attention as fa
    return fa.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=dropout_p, is_causal=is_causal,
        training=training, scale=scale)


# ---- misc ------------------------------------------------------------------

def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


def label_smooth(label, epsilon=0.1):
    n = label.shape[-1]
    return label * (1 - epsilon) + epsilon / n


def normalize(x, p=2, axis=1, epsilon=1e-12):
    denom = jnp.maximum(jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True), epsilon)
    return x / denom


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot_ = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot_ / jnp.maximum(n1 * n2, eps)
