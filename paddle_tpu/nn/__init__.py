from paddle_tpu.nn.layer import Layer, Parameter, functional_call, make_apply  # noqa: F401
from paddle_tpu.nn import functional  # noqa: F401
from paddle_tpu.nn import initializer  # noqa: F401
from paddle_tpu.nn.layers.common import (  # noqa: F401
    Linear,
    Embedding,
    Dropout,
    Identity,
    Flatten,
    ReLU,
    ReLU6,
    GELU,
    Silu,
    Sigmoid,
    Tanh,
    Softmax,
    LeakyReLU,
    Hardswish,
    Mish,
    Sequential,
    LayerList,
    LayerDict,
    ParameterList,
)
from paddle_tpu.nn.layers.norm import (  # noqa: F401
    LayerNorm,
    RMSNorm,
    BatchNorm,
    BatchNorm2D,
    GroupNorm,
)
from paddle_tpu.nn.layers.conv import (  # noqa: F401
    Conv1D,
    Conv2D,
    Conv2DTranspose,
    MaxPool2D,
    AvgPool2D,
    AdaptiveAvgPool2D,
    Upsample,
)
from paddle_tpu.nn.layers.transformer import (  # noqa: F401
    MultiHeadAttention,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from paddle_tpu.nn.layers.rnn import (  # noqa: F401
    SimpleRNN,
    LSTM,
    GRU,
    SimpleRNNCell,
    LSTMCell,
    GRUCell,
)
from paddle_tpu.nn.layers.moe import (  # noqa: F401
    MoELayer,
    GShardGate,
    SwitchGate,
)
from paddle_tpu.nn.loss import (  # noqa: F401
    CrossEntropyLoss,
    MSELoss,
    L1Loss,
    NLLLoss,
    BCEWithLogitsLoss,
    KLDivLoss,
)
