"""Activation Layer classes (reference: python/paddle/nn/layer/activation.py).

Class-per-activation veneers over `paddle_tpu.nn.functional`; PReLU is the
one with a learnable parameter.
"""

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.nn.layer import Layer


class ELU(Layer):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0):
        super().__init__()
        self.beta = beta

    def forward(self, x):
        return F.softplus(x, self.beta)


class Softshrink(Layer):
    def __init__(self, threshold=0.5):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class LogSigmoid(Layer):
    def forward(self, x):
        return F.log_sigmoid(x)


class LogSoftmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class Softsign(Layer):
    def forward(self, x):
        return F.softsign(x)


class Tanhshrink(Layer):
    def forward(self, x):
        return F.tanhshrink(x)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold)


class Swish(Layer):
    def forward(self, x):
        return F.silu(x)


class GLU(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, axis=self.axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class PReLU(Layer):
    """Learnable negative slope (reference: nn.PReLU(num_parameters, init))."""

    def __init__(self, num_parameters=1, init_value=0.25, dtype=None):
        super().__init__()
        self.weight = self.create_parameter(
            (num_parameters,), dtype=dtype,
            default_initializer=init.Constant(init_value))

    def forward(self, x):
        return F.prelu(x, self.weight)


class Hardsigmoid(Layer):
    def forward(self, x):
        return F.hardsigmoid(x)
