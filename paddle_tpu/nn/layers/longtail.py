"""nn layer long tail (VERDICT r2 #7) — the commonly-hit stragglers.

Reference: python/paddle/nn/layer/{pooling,loss,rnn,norm,vision}.py. Each
class follows the repo's veneer discipline: a thin Layer over a jnp/XLA
composition, paddle argument orders, tested numerically in
tests/test_longtail.py.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.nn.layers.rnn import _RNNCellBase

# reference exposes the grad-clip configs under paddle.nn as well
from paddle_tpu.optimizer.clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)

RNNCellBase = _RNNCellBase


# ---- pooling ---------------------------------------------------------------

class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        n, c, l = x.shape
        o = self.output_size if isinstance(self.output_size, int) \
            else self.output_size[0]
        assert l % o == 0, "adaptive pool needs divisible sizes"
        w = l // o
        r = x.reshape(n, c, o, w)
        out = jnp.max(r, axis=-1)
        if self.return_mask:
            idx = jnp.argmax(r, axis=-1) + jnp.arange(o)[None, None] * w
            return out, idx
        return out


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        n, c, d, h, w = x.shape
        od, oh, ow = ((self.output_size,) * 3
                      if isinstance(self.output_size, int)
                      else tuple(self.output_size))
        assert d % od == 0 and h % oh == 0 and w % ow == 0
        kd, kh, kw = d // od, h // oh, w // ow
        r = x.reshape(n, c, od, kd, oh, kh, ow, kw)
        out = jnp.max(r, axis=(3, 5, 7))
        if not self.return_mask:
            return out
        # flat (d*h*w) index of each max, reference mask convention
        win = jnp.moveaxis(r, (3, 5, 7), (5, 6, 7)).reshape(
            n, c, od, oh, ow, kd * kh * kw)
        arg = jnp.argmax(win, axis=-1)
        ld, rem = arg // (kh * kw), arg % (kh * kw)
        lh, lw = rem // kw, rem % kw
        gd = jnp.arange(od)[:, None, None] * kd + ld
        gh = jnp.arange(oh)[None, :, None] * kh + lh
        gw = jnp.arange(ow)[None, None, :] * kw + lw
        return out, (gd * h + gh) * w + gw


class MaxUnPool1D(Layer):
    """Inverse of max_pool1d(return_mask=True): values land at `indices`
    (flat positions within each (L,) plane), zeros elsewhere."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None):
        super().__init__()
        self.kernel = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding
        self.output_size = output_size

    def out_len(self, l):
        if self.output_size is not None:
            return (self.output_size if isinstance(self.output_size, int)
                    else self.output_size[-1])
        return (l - 1) * self.stride - 2 * self.padding + self.kernel

    def forward(self, x, indices):
        n, c, l = x.shape
        out = jnp.zeros((n, c, self.out_len(l)), x.dtype)
        return out.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            indices].set(x)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None):
        super().__init__()
        k = (kernel_size,) * 2 if isinstance(kernel_size, int) else kernel_size
        s = stride or k
        self.k = k
        self.s = (s,) * 2 if isinstance(s, int) else s
        self.p = (padding,) * 2 if isinstance(padding, int) else padding
        self.output_size = output_size

    def forward(self, x, indices):
        n, c, h, w = x.shape
        if self.output_size is not None:
            oh, ow = self.output_size[-2:]
        else:
            oh = (h - 1) * self.s[0] - 2 * self.p[0] + self.k[0]
            ow = (w - 1) * self.s[1] - 2 * self.p[1] + self.k[1]
        flat = jnp.zeros((n, c, oh * ow), x.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            indices.reshape(n, c, -1)].set(x.reshape(n, c, -1))
        return flat.reshape(n, c, oh, ow)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None):
        super().__init__()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) else kernel_size
        s = stride or k
        self.k = k
        self.s = (s,) * 3 if isinstance(s, int) else s
        self.p = (padding,) * 3 if isinstance(padding, int) else padding
        self.output_size = output_size

    def forward(self, x, indices):
        n, c, d, h, w = x.shape
        if self.output_size is not None:
            od, oh, ow = self.output_size[-3:]
        else:
            od = (d - 1) * self.s[0] - 2 * self.p[0] + self.k[0]
            oh = (h - 1) * self.s[1] - 2 * self.p[1] + self.k[1]
            ow = (w - 1) * self.s[2] - 2 * self.p[2] + self.k[2]
        flat = jnp.zeros((n, c, od * oh * ow), x.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            indices.reshape(n, c, -1)].set(x.reshape(n, c, -1))
        return flat.reshape(n, c, od, oh, ow)


class LPPool1D(Layer):
    """(Σ window x^p)^(1/p) (reference paddle.nn.LPPool1D). The window
    SUM (and ceil_mode window math) comes from F._pool — avg_pool's
    exclusive counts would mis-scale padded edge windows."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL"):
        super().__init__()
        self.p = float(norm_type)
        self.nd = 1
        self.args = (kernel_size, stride or kernel_size, padding, ceil_mode)

    def forward(self, x):
        k, s, p, cm = self.args
        sums = F._pool(x ** self.p, k, s, p, self.nd, jax.lax.add, 0.0,
                       ceil_mode=cm)
        return sums ** (1.0 / self.p)


class LPPool2D(LPPool1D):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW"):
        super().__init__(norm_type, kernel_size, stride, padding, ceil_mode)
        self.nd = 2


def _fractional_starts(n_in, n_out, u):
    """Deterministic fractional-pool boundaries (pseudorandom index
    sequence of Graham's fractional max-pooling, with fixed u)."""
    alpha = n_in / n_out
    idx = np.floor(alpha * (np.arange(n_out) + u)).astype(np.int64)
    idx = np.clip(idx, 0, n_in - 1)
    ends = np.append(idx[1:], n_in)
    return idx, np.maximum(ends - idx, 1)


class FractionalMaxPool2D(Layer):
    """Fractional max pooling (Graham 2014). `random_u` fixes the
    pseudorandom boundary offset (defaults to 0.5; the reference samples
    it per call in training)."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False):
        super().__init__()
        if return_mask:
            raise NotImplementedError(
                "FractionalMaxPool return_mask is not implemented")
        if kernel_size is not None:
            raise NotImplementedError(
                "FractionalMaxPool kernel_size overlap mode is not "
                "implemented (boundary windows only)")
        self.output_size = ((output_size,) * 2
                            if isinstance(output_size, int) else output_size)
        self.u = 0.5 if random_u is None else float(random_u)

    def forward(self, x):
        n, c, h, w = x.shape
        oh, ow = self.output_size
        hs, hl = _fractional_starts(h, oh, self.u)
        ws, wl = _fractional_starts(w, ow, self.u)
        wmax_h, wmax_w = int(hl.max()), int(wl.max())
        hidx = np.minimum(hs[:, None] + np.arange(wmax_h)[None], h - 1)
        widx = np.minimum(ws[:, None] + np.arange(wmax_w)[None], w - 1)
        hmask = np.arange(wmax_h)[None] < hl[:, None]
        wmask = np.arange(wmax_w)[None] < wl[:, None]
        patches = x[:, :, jnp.asarray(hidx)[:, :, None, None],
                    jnp.asarray(widx)[None, None]]
        mask = jnp.asarray(hmask[:, :, None, None] & wmask[None, None])
        patches = jnp.where(mask, patches, -jnp.inf)
        return jnp.max(patches, axis=(3, 5))


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False):
        super().__init__()
        if return_mask or kernel_size is not None:
            raise NotImplementedError(
                "FractionalMaxPool3D supports boundary windows only, "
                "without return_mask")
        self.output_size = ((output_size,) * 3
                            if isinstance(output_size, int) else output_size)
        self.u = 0.5 if random_u is None else float(random_u)

    def forward(self, x):
        n, c, d, h, w = x.shape
        od, oh, ow = self.output_size
        # factor through the 2D case on (d) then (h, w)
        ds, dl = _fractional_starts(d, od, self.u)
        wmax_d = int(dl.max())
        didx = np.minimum(ds[:, None] + np.arange(wmax_d)[None], d - 1)
        dmask = np.arange(wmax_d)[None] < dl[:, None]
        slabs = x[:, :, jnp.asarray(didx)]           # (n, c, od, wd, h, w)
        slabs = jnp.where(jnp.asarray(dmask)[:, :, None, None], slabs,
                          -jnp.inf)
        slabs = jnp.max(slabs, axis=3)               # (n, c, od, h, w)
        pool2d = FractionalMaxPool2D((oh, ow), random_u=self.u)
        return jax.vmap(pool2d.forward, in_axes=2, out_axes=2)(slabs)


# ---- conv ------------------------------------------------------------------

class Conv1DTranspose(Layer):
    """weight (in_ch, out_ch/groups, k) — via the 2-D transpose conv."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        assert groups == 1 and dilation == 1, "parity subset"
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) \
            else init.XavierNormal()
        self.weight = self.create_parameter(
            (in_channels, out_channels, kernel_size),
            default_initializer=w_init)
        self.bias = (self.create_parameter((out_channels,), is_bias=True)
                     if bias_attr is not False else None)
        self.args = (stride, padding, output_padding)

    def forward(self, x):
        s, p, op = self.args
        y = F.conv2d_transpose(x[:, :, None], self.weight[:, :, None],
                               bias=self.bias, stride=(1, s),
                               padding=(0, p), output_padding=(0, op))
        return y[:, :, 0]


# ---- norm / reparametrization ---------------------------------------------

class SpectralNorm(Layer):
    """Spectral normalization of a given weight (reference
    paddle.nn.SpectralNorm): forward(weight) -> weight / sigma_max, with
    power-iteration vectors kept as buffers."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.register_buffer("weight_u", jnp.asarray(
            np.random.RandomState(0).randn(h).astype(np.float32)))
        self.register_buffer("weight_v", jnp.asarray(
            np.random.RandomState(1).randn(w).astype(np.float32)))

    def forward(self, weight):
        mat = jnp.moveaxis(weight, self.dim, 0).reshape(
            weight.shape[self.dim], -1)
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        # persist the iteration (reference updates in place each forward
        # so the estimate converges across steps; under functional_call
        # the update applies to the eager buffers only). Under jit/grad the
        # values are tracers — storing those on the eager module would leak
        # them (UnexpectedTracerError on the next eager use), so persist
        # only concrete values.
        if not isinstance(u, jax.core.Tracer):
            self._buffers["weight_u"] = jax.lax.stop_gradient(u)
            self._buffers["weight_v"] = jax.lax.stop_gradient(v)
        sigma = u @ mat @ v
        return weight / sigma


# ---- activations / shapes --------------------------------------------------

class Softmax2D(Layer):
    """Softmax over the channel dim of (N, C, H, W)."""

    def forward(self, x):
        assert x.ndim == 4
        return jax.nn.softmax(x, axis=-3)


# ---- losses ----------------------------------------------------------------

def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean"):
        super().__init__()
        self.full, self.eps, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        var = jnp.clip(variance, self.eps, None)
        loss = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
        if self.full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean"):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):
        n, c = input.shape
        x_y = jnp.take_along_axis(input, label[:, None], axis=1)
        hinge = jnp.maximum(0.0, self.margin - x_y + input) ** self.p
        if self.weight is not None:
            hinge = hinge * jnp.take(self.weight, label)[:, None]
        # the j == y term is margin^p; subtract it out
        own = jnp.maximum(0.0, jnp.asarray(self.margin)) ** self.p
        if self.weight is not None:
            own = own * jnp.take(self.weight, label)[:, None]
        loss = (jnp.sum(hinge, axis=1, keepdims=True) - own) / c
        return _reduce(loss[:, 0], self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean"):
        super().__init__()
        self.dist = distance_function or (
            lambda a, b: jnp.linalg.norm(a - b, axis=-1))
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, anchor, positive, negative):
        d_pos = self.dist(anchor, positive)
        d_neg = self.dist(anchor, negative)
        if self.swap:
            d_neg = jnp.minimum(d_neg, self.dist(positive, negative))
        return _reduce(jnp.maximum(0.0, d_pos - d_neg + self.margin),
                       self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid over the default complete binary tree
    (reference paddle.nn.HSigmoidLoss without custom paths): class c's
    path is the binary decomposition of c + num_classes in the implicit
    heap of 2*num_classes-1 nodes."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False):
        super().__init__()
        assert not is_custom, "custom path tables: pass path_table/path_code"
        self.num_classes = num_classes
        w = weight_attr if isinstance(weight_attr, init.Initializer) \
            else init.XavierNormal()
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), default_initializer=w)
        self.bias = (self.create_parameter((num_classes - 1,), is_bias=True)
                     if bias_attr is not False else None)
        # static per-class paths through the implicit heap
        depth = max(1, int(math.ceil(math.log2(max(num_classes, 2)))))
        table = np.zeros((num_classes, depth), np.int64)
        code = np.zeros((num_classes, depth), np.float32)
        lens = np.zeros((num_classes,), np.int64)
        for c in range(num_classes):
            node = c + num_classes        # leaf id in the heap
            path = []
            while node > 1:
                path.append((node // 2 - 1, float(node % 2)))
                node //= 2
            path = path[::-1][:depth]
            lens[c] = len(path)
            for i, (nid, bit) in enumerate(path):
                table[c, i] = min(nid, num_classes - 2)
                code[c, i] = bit
        self._table = jnp.asarray(table)
        self._code = jnp.asarray(code)
        self._lens = jnp.asarray(lens)

    def forward(self, input, label):
        nodes = jnp.take(self._table, label, axis=0)     # (n, depth)
        codes = jnp.take(self._code, label, axis=0)
        lens = jnp.take(self._lens, label)
        w = jnp.take(self.weight, nodes, axis=0)         # (n, depth, f)
        logits = jnp.einsum("nf,ndf->nd", input, w)
        if self.bias is not None:
            logits = logits + jnp.take(self.bias, nodes)
        # sigmoid CE against the path code, masked to the real path length
        valid = jnp.arange(nodes.shape[1])[None] < lens[:, None]
        ce = jnp.maximum(logits, 0) - logits * codes + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(jnp.where(valid, ce, 0.0), axis=1, keepdims=True)


# ---- recurrent wrappers ----------------------------------------------------

class RNN(Layer):
    """Run `cell` over the time dim with lax.scan (reference
    paddle.nn.RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None):
        x = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)
        if self.is_reverse:
            x = jnp.flip(x, axis=0)
        b = x.shape[1]
        h = self.cell.hidden_size
        if initial_states is None:
            z = jnp.zeros((b, h), x.dtype)
            initial_states = (z, z) if getattr(self.cell, "n_gates", 1) == 4 \
                else z

        from paddle_tpu.nn.layer import functional_call
        st = self.cell.state_dict(include_buffers=False)

        def step(carry, xt):
            # cells return the new state (LSTM: (h, c)); output is h
            new = functional_call(self.cell, st, xt, carry)
            out = new[0] if isinstance(new, tuple) else new
            return new, out

        last, outs = jax.lax.scan(step, initial_states, x)
        if self.is_reverse:
            outs = jnp.flip(outs, axis=0)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, last


class BiRNN(Layer):
    """Bidirectional cell wrapper (reference paddle.nn.BiRNN): forward and
    backward cells run over the sequence, outputs concatenated."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None):
        fw_init, bw_init = (initial_states if initial_states is not None
                            else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, fw_init)
        out_bw, st_bw = self.rnn_bw(inputs, bw_init)
        return jnp.concatenate([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax (Grave et al.; reference
    paddle.nn.AdaptiveLogSoftmaxWithLoss): frequent classes in a full head,
    rare classes in down-projected tail clusters."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False):
        super().__init__()
        cutoffs = list(cutoffs)
        assert cutoffs == sorted(cutoffs) and cutoffs[-1] < n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        w = init.XavierNormal()
        self.head_weight = self.create_parameter(
            (in_features, self.head_size), default_initializer=w)
        self.head_bias = (self.create_parameter((self.head_size,),
                                                is_bias=True)
                          if head_bias else None)
        self.tail_proj = []
        self.tail_out = []
        for i in range(self.n_clusters):
            dim = max(1, int(in_features / (div_value ** (i + 1))))
            size = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter((in_features, dim),
                                         default_initializer=w)
            out = self.create_parameter((dim, size), default_initializer=w)
            self._parameters[f"tail_proj_{i}"] = proj
            self._parameters[f"tail_out_{i}"] = out
            self.tail_proj.append(proj)
            self.tail_out.append(out)

    def log_prob(self, input):
        """Full (n, n_classes) log-probabilities."""
        head = input @ self.head_weight
        if self.head_bias is not None:
            head = head + self.head_bias
        head_lp = jax.nn.log_softmax(head, axis=-1)
        parts = [head_lp[:, :self.cutoffs[0]]]
        for i in range(self.n_clusters):
            proj = self._parameters[f"tail_proj_{i}"].value
            out = self._parameters[f"tail_out_{i}"].value
            tail_lp = jax.nn.log_softmax((input @ proj) @ out, axis=-1)
            parts.append(head_lp[:, self.cutoffs[0] + i:None][:, :1]
                         + tail_lp)
        return jnp.concatenate(parts, axis=-1)

    def forward(self, input, label):
        lp = self.log_prob(input)
        nll = -jnp.take_along_axis(lp, label[:, None], axis=1)[:, 0]
        return nll, jnp.mean(nll)


class BeamSearchDecoder(Layer):
    """Beam search over a cell (compact reference-parity core of
    paddle.nn.BeamSearchDecoder): expand each beam by the top-k next
    tokens, keep the best k sequences by cumulative log-prob. Used through
    `dynamic_decode`."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn, output_fn):
        super().__init__()
        self.cell = cell
        self.start, self.end = start_token, end_token
        self.k = beam_size
        self.embed = embedding_fn
        self.output_fn = output_fn

    def decode(self, batch_size, max_steps, initial_state=None):
        from paddle_tpu.nn.layer import functional_call
        k = self.k
        st = self.cell.state_dict(include_buffers=False)
        h = self.cell.hidden_size
        n_states = 2 if getattr(self.cell, "n_gates", 1) == 4 else 1

        def zstate():
            z = jnp.zeros((batch_size * k, h), jnp.float32)
            return (z, z) if n_states == 2 else z

        state = initial_state if initial_state is not None else zstate()
        tok = jnp.full((batch_size, k), self.start, jnp.int32)
        # only beam 0 live at t=0 so the first expansion is not degenerate
        scores = jnp.tile(jnp.asarray([[0.0] + [-1e9] * (k - 1)]),
                          (batch_size, 1))
        seqs = jnp.zeros((batch_size, k, max_steps), jnp.int32)
        done = jnp.zeros((batch_size, k), bool)

        for t in range(max_steps):
            x = self.embed(tok.reshape(-1))
            state = functional_call(self.cell, st, x, state)
            out = state[0] if isinstance(state, tuple) else state
            logits = self.output_fn(out)                  # (b*k, vocab)
            lp = jax.nn.log_softmax(logits, -1).reshape(batch_size, k, -1)
            lp = jnp.where(done[..., None], -1e9, lp)
            # finished beams keep emitting end at no cost
            lp = lp.at[:, :, self.end].set(
                jnp.where(done, 0.0, lp[:, :, self.end]))
            vocab = lp.shape[-1]
            total = scores[..., None] + lp                # (b, k, V)
            scores, flat = jax.lax.top_k(total.reshape(batch_size, -1), k)
            beam = flat // vocab
            tok = flat % vocab
            seqs = jnp.take_along_axis(
                seqs, beam[..., None], axis=1).at[:, :, t].set(tok)
            done = jnp.take_along_axis(done, beam, axis=1) | \
                (tok == self.end)
            reindex = (beam + jnp.arange(batch_size)[:, None] * k).reshape(-1)
            state = jax.tree.map(lambda s: jnp.take(s, reindex, axis=0),
                                 state)
        return seqs, scores


def dynamic_decode(decoder, inits=None, max_step_num=32, batch_size=1,
                   **kwargs):
    """Run a BeamSearchDecoder to completion (reference
    paddle.nn.dynamic_decode core form). Returns (sequences, scores)."""
    return decoder.decode(batch_size, max_step_num, initial_state=inits)
