"""Normalization layers: LayerNorm, RMSNorm, BatchNorm2D, GroupNorm.

Ref: python/paddle/nn/layer/norm.py. RMSNorm routes through the Pallas kernel
(paddle_tpu/ops/rms_norm.py ≈ the reference's phi rms_norm fusion kernel,
paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu).
"""

import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None, dtype=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, dtype=dtype,
                default_initializer=init.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self.normalized_shape, dtype=dtype,
                default_initializer=init.Constant(0.0), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        w = self.weight if "weight" in self._parameters else None
        b = self.bias if "bias" in self._parameters else None
        return F.layer_norm(x, self.normalized_shape, w, b, self.epsilon)


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, dtype=None):
        super().__init__()
        self.weight = self.create_parameter(
            (hidden_size,), dtype=dtype, default_initializer=init.Constant(1.0))
        self.epsilon = epsilon

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class BatchNorm2D(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_features,), default_initializer=init.Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), default_initializer=init.Constant(0.0), is_bias=True)
        self.register_buffer("_mean", jnp.zeros((num_features,)))
        self.register_buffer("_variance", jnp.ones((num_features,)))

    def forward(self, x):
        y, new_rm, new_rv = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format)
        if self.training:
            self._buffers["_mean"] = new_rm
            self._buffers["_variance"] = new_rv
        return y


BatchNorm = BatchNorm2D


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_channels,), default_initializer=init.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (num_channels,), default_initializer=init.Constant(0.0), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        w = self.weight if "weight" in self._parameters else None
        b = self.bias if "bias" in self._parameters else None
        return F.group_norm(x, self.num_groups, w, b, self.epsilon, self.data_format)
