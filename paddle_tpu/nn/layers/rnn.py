"""Recurrent layers: SimpleRNN, LSTM, GRU (ref: python/paddle/nn/layer/rnn.py).

TPU-first: the time loop is lax.scan (XLA unrolls/pipelines it); gates are
single fused matmuls per step. Batch-first (b, s, input) like the reference's
time_major=False default; multi-layer and bidirectional supported.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init


class _RNNCellBase(Layer):
    n_gates = 1
    act = staticmethod(jnp.tanh)

    def __init__(self, input_size, hidden_size):
        super().__init__()
        k = 1.0 / (hidden_size ** 0.5)
        u = init.Uniform(-k, k)
        g = self.n_gates
        self.weight_ih = self.create_parameter((input_size, g * hidden_size),
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((hidden_size, g * hidden_size),
                                               default_initializer=u)
        self.bias_ih = self.create_parameter((g * hidden_size,),
                                             default_initializer=u, is_bias=True)
        self.bias_hh = self.create_parameter((g * hidden_size,),
                                             default_initializer=u, is_bias=True)
        self.hidden_size = hidden_size
        self.input_size = input_size


class SimpleRNNCell(_RNNCellBase):
    def forward(self, x, state):
        h = state
        z = x @ self.weight_ih + self.bias_ih + h @ self.weight_hh + self.bias_hh
        return type(self).act(z)


class LSTMCell(_RNNCellBase):
    n_gates = 4

    def forward(self, x, state):
        h, c = state
        z = x @ self.weight_ih + self.bias_ih + h @ self.weight_hh + self.bias_hh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        c = f * c + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return h, c


class GRUCell(_RNNCellBase):
    n_gates = 3

    def forward(self, x, state):
        h = state
        zi = x @ self.weight_ih + self.bias_ih
        zh = h @ self.weight_hh + self.bias_hh
        ri, ui, ci = jnp.split(zi, 3, axis=-1)
        rh, uh, ch = jnp.split(zh, 3, axis=-1)
        r = F.sigmoid(ri + rh)
        u = F.sigmoid(ui + uh)
        cand = jnp.tanh(ci + r * ch)
        return u * h + (1.0 - u) * cand


class _RNNBase(Layer):
    cell_cls = SimpleRNNCell
    has_c = False

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", dropout=0.0, time_major=False):
        super().__init__()
        self.bidirect = direction in ("bidirect", "bidirectional")
        n_dir = 2 if self.bidirect else 1
        cells = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * n_dir
            cells.append(self.cell_cls(in_sz, hidden_size))
            if self.bidirect:
                cells.append(self.cell_cls(in_sz, hidden_size))
        from paddle_tpu.nn.layers.common import LayerList
        self.cells = LayerList(cells)
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.time_major = time_major
        self.dropout = dropout

    def _zero_state(self, b):
        h = jnp.zeros((b, self.hidden_size))
        return (h, jnp.zeros_like(h)) if self.has_c else h

    def _run_cell(self, cell, x, init_state=None, reverse=False):
        """x: (b, s, in) → outputs (b, s, hidden), final state."""
        xs = jnp.swapaxes(x, 0, 1)               # (s, b, in)
        if reverse:
            xs = xs[::-1]
        # bind the cell's state once so scan traces a pure step
        from paddle_tpu.nn.layer import functional_call
        cell_state = cell.state_dict()

        def step(carry, xt):
            out = functional_call(cell, cell_state, xt, carry)
            h = out[0] if self.has_c else out
            return out, h

        carry0 = (self._zero_state(x.shape[0]) if init_state is None
                  else init_state)
        final, hs = jax.lax.scan(step, carry0, xs)
        if reverse:
            hs = hs[::-1]
        return jnp.swapaxes(hs, 0, 1), final

    def _initial_state(self, initial_states, idx):
        """State for (layer, direction) slot `idx` from the stacked
        (num_layers * n_dir, b, hidden) initial_states (h or (h, c))."""
        if initial_states is None:
            return None
        if self.has_c:
            h0, c0 = initial_states
            return (h0[idx], c0[idx])
        return initial_states[idx]

    def forward(self, x, initial_states=None):
        if self.time_major:
            x = jnp.swapaxes(x, 0, 1)
        finals = []
        for layer in range(self.num_layers):
            if self.bidirect:
                fwd_cell = self.cells[2 * layer]
                bwd_cell = self.cells[2 * layer + 1]
                out_f, fin_f = self._run_cell(
                    fwd_cell, x,
                    init_state=self._initial_state(initial_states, 2 * layer))
                out_b, fin_b = self._run_cell(
                    bwd_cell, x, reverse=True,
                    init_state=self._initial_state(initial_states,
                                                   2 * layer + 1))
                x = jnp.concatenate([out_f, out_b], axis=-1)
                finals.extend([fin_f, fin_b])
            else:
                x, fin = self._run_cell(
                    self.cells[layer], x,
                    init_state=self._initial_state(initial_states, layer))
                finals.append(fin)
            if self.dropout and layer < self.num_layers - 1:
                x = F.dropout(x, self.dropout, training=self.training)
        if self.has_c:
            h = jnp.stack([f[0] for f in finals])
            c = jnp.stack([f[1] for f in finals])
            final_state = (h, c)
        else:
            final_state = jnp.stack(finals)
        if self.time_major:
            x = jnp.swapaxes(x, 0, 1)
        return x, final_state


class SimpleRNN(_RNNBase):
    cell_cls = SimpleRNNCell


class LSTM(_RNNBase):
    cell_cls = LSTMCell
    has_c = True


class GRU(_RNNBase):
    cell_cls = GRUCell
