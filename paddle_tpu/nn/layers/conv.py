"""Convolution and pooling layers (ref: python/paddle/nn/layer/conv.py, pooling.py)."""

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init


class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW", dtype=None):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (tuple, list)) else (kernel_size, kernel_size)
        fan_in = in_channels // groups * k[0] * k[1]
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) else \
            init.KaimingUniform(fan_in=fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, k[0], k[1]), dtype=dtype,
            default_initializer=w_init)
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), dtype=dtype, default_initializer=init.Constant(0.0),
                is_bias=True)
        else:
            self.bias = None
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        b = self.bias if "bias" in self._parameters else None
        return F.conv2d(x, self.weight, b, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None, dtype=None):
        super().__init__()
        fan_in = in_channels // groups * kernel_size
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kernel_size), dtype=dtype,
            default_initializer=init.KaimingUniform(fan_in=fan_in))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), dtype=dtype, default_initializer=init.Constant(0.0),
                is_bias=True)
        else:
            self.bias = None
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups

    def forward(self, x):
        b = self.bias if "bias" in self._parameters else None
        return F.conv1d(x, self.weight, b, self.stride, self.padding,
                        self.dilation, self.groups)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, bias_attr=None,
                 data_format="NCHW", dtype=None):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (tuple, list)) else (kernel_size, kernel_size)
        self.weight = self.create_parameter(
            (in_channels, out_channels, k[0], k[1]), dtype=dtype,
            default_initializer=init.KaimingUniform(fan_in=in_channels * k[0] * k[1]))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), dtype=dtype, default_initializer=init.Constant(0.0),
                is_bias=True)
        else:
            self.bias = None
        self.stride, self.padding, self.output_padding = stride, padding, output_padding
        self.data_format = data_format

    def forward(self, x):
        b = self.bias if "bias" in self._parameters else None
        return F.conv2d_transpose(x, self.weight, b, self.stride, self.padding,
                                  self.output_padding, self.data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor, self.mode = size, scale_factor, mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.scale_factor, self.size, self.mode,
                             self.data_format)
