"""Mixture-of-experts: top-k gating + expert-parallel grouped experts.

Reference (SURVEY.md §2.6-EP): `MoELayer` with GShard top-2 / Switch top-1
gates (python/paddle/incubate/distributed/models/moe/{moe_layer.py,gate/}),
token dispatch via the `global_scatter`/`global_gather` NCCL all-to-all ops
(paddle/fluid/operators/collective/global_scatter_op.cu).

TPU-first design:
* experts live as ONE grouped weight per projection, shape
  (num_experts, d_in, d_out), expert dim sharded over the expert-parallel
  mesh axes — a single einsum runs all local experts on the MXU.
* dispatch/combine are GShard-style one-hot capacity tensors; constraining
  the dispatched activations to the expert sharding makes GSPMD emit the
  all_to_all the reference issues by hand.
* capacity is static (capacity_factor · k · tokens / E) so shapes stay
  XLA-friendly; overflow tokens are dropped exactly like the reference.
* the load-balancing aux loss is returned alongside the output; model code
  adds it to the task loss (the pipeline schedule threads it per-stage).
"""

import functools
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.parallel.mp_layers import constrain

EP_AXES = ("dp",)   # default: expert parallelism rides the dp axis


def _ep_spec(ep_axes, ndim, extra=None):
    """Spec sharding dim0 (experts) over ep_axes (replicated when empty —
    the dropless path); `extra` maps dim→axis."""
    dims = [None] * ndim
    if ep_axes:
        dims[0] = ep_axes if len(ep_axes) > 1 else ep_axes[0]
    for d, a in (extra or {}).items():
        dims[d] = a
    return P(*dims)


def _swiglu(xe, wg, wu, wd):
    """(E, C, h) grouped SwiGLU — the one expert-FFN math, shared by every
    dispatch path."""
    h1 = jnp.einsum("ech,ehf->ecf", xe, wg)
    h2 = jnp.einsum("ech,ehf->ecf", xe, wu)
    return jnp.einsum("ecf,efh->ech", F.silu(h1) * h2, wd)


def _slots(idx, pos, keep, cap, e):
    """Copy→slot map (t·k,): kept copies get unique slots in [0, e·cap);
    dropped copies get the OUT-OF-BOUNDS value e·cap (mode="drop" scatters
    discard them — never an in-bounds duplicate)."""
    return jnp.where(keep, idx * cap + pos, e * cap).reshape(-1)


def _token_copies(xt, k):
    """(t, h) → (t·k, h) row copies; the broadcast's VJP sums the k
    copy-grads back per token."""
    t, h = xt.shape
    return jnp.broadcast_to(xt[:, None], (t, k, h)).reshape(t * k, h)


def _slot_scatter(xt, idx, pos, keep, cap, e):
    """Tokens → flat (e·cap, h) expert buffer; dropped tokens get an OOB
    slot the scatter drops. Returns (buffer, slot ids)."""
    slot = _slots(idx, pos, keep, cap, e)
    xt_k = _token_copies(xt, idx.shape[1])
    buf = jnp.zeros((e * cap, xt.shape[-1]), xt.dtype).at[slot].set(
        xt_k, mode="drop", unique_indices=True)
    return buf, slot


def _slot_combine(ye_flat, slot, vals, keep, dtype):
    """Gather expert outputs back by slot and mix with gate weights."""
    t, k = vals.shape
    h = ye_flat.shape[-1]
    gathered = jnp.take(ye_flat, slot, axis=0, mode="fill",
                        fill_value=0).reshape(t, k, h)
    w = (vals * keep).astype(dtype)
    return jnp.einsum("tk,tkh->th", w, gathered)


def _perm_maps(slot, e, cap, tk):
    """Invert the copy→slot map: (buf_src (E·cap,) int, hit (E·cap,) bool)
    give, for every expert-buffer slot, which token-copy fills it (if any).

    One int32 scatter of tk scalars. Kept copies have unique in-bounds
    slots; dropped copies carry the OUT-OF-BOUNDS slot e*cap, which
    mode="drop" discards — so unique_indices holds. Cheap: the expensive
    ROW movement all happens as gathers — see _permute_rows."""
    buf_src = jnp.full((e * cap,), tk, jnp.int32).at[slot].set(
        jnp.arange(tk, dtype=jnp.int32), mode="drop", unique_indices=True)
    hit = buf_src < tk
    return jnp.where(hit, buf_src, 0), hit


@jax.custom_vjp
def _permute_rows(x, fwd_idx, fwd_ok, bwd_idx, bwd_ok):
    """out[i] = fwd_ok[i] ? x[fwd_idx[i]] : 0 — a (partial) row
    permutation whose backward is the INVERSE gather (bwd_idx/bwd_ok), so
    neither direction lowers to an XLA scatter (TPU scatters serialize
    row-by-row; gathers run at bandwidth). The index sets must be mutually
    inverse over their valid entries."""
    out = jnp.take(x, jnp.where(fwd_ok, fwd_idx, 0), axis=0)
    return jnp.where(fwd_ok[:, None], out, 0)


def _permute_rows_fwd(x, fwd_idx, fwd_ok, bwd_idx, bwd_ok):
    return _permute_rows(x, fwd_idx, fwd_ok, bwd_idx, bwd_ok), \
        (fwd_idx, fwd_ok, bwd_idx, bwd_ok)


def _permute_rows_bwd(res, g):
    fwd_idx, fwd_ok, bwd_idx, bwd_ok = res
    dx = jnp.take(g, jnp.where(bwd_ok, bwd_idx, 0), axis=0)
    dx = jnp.where(bwd_ok[:, None], dx, 0)
    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return dx, f0(fwd_idx), f0(fwd_ok), f0(bwd_idx), f0(bwd_ok)


_permute_rows.defvjp(_permute_rows_fwd, _permute_rows_bwd)


def _f0(a):
    return np.zeros(a.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _gather_dispatch(xt, buf_src, hit, slot_cl, keep, k):
    """out[s] = hit[s] ? xt[buf_src[s] // k] : 0 — dispatch straight from
    the (t, h) token rows into the flat (E·cap, h) per-expert blocks.

    Unlike `_forward_sort`'s two-step (materialize (t·k, h) row copies,
    then permute them), the token index is recovered from the copy index
    in the gather itself, so the expensive row movement is ONE gather per
    direction and the (t·k, h) intermediate never exists. Backward is the
    inverse gather (slot_cl/keep) followed by a contiguous segment-sum
    over each token's k copy rows — no row scatter anywhere."""
    out = jnp.take(xt, jnp.where(hit, buf_src // k, 0), axis=0)
    return jnp.where(hit[:, None], out, 0)


def _gather_dispatch_fwd(xt, buf_src, hit, slot_cl, keep, k):
    return _gather_dispatch(xt, buf_src, hit, slot_cl, keep, k), \
        (buf_src, hit, slot_cl, keep)


def _gather_dispatch_bwd(k, res, g):
    buf_src, hit, slot_cl, keep = res
    rows = jnp.take(g, jnp.where(keep, slot_cl, 0), axis=0)
    rows = jnp.where(keep[:, None], rows, 0)            # (t·k, h)
    t = keep.shape[0] // k
    dx = rows.reshape(t, k, -1).sum(axis=1)             # segment-sum
    return dx, _f0(buf_src), _f0(hit), _f0(slot_cl), _f0(keep)


_gather_dispatch.defvjp(_gather_dispatch_fwd, _gather_dispatch_bwd)


@jax.custom_vjp
def _combine_gather(ye, w, slot_cl, keep, buf_src, hit):
    """yt[t] = Σ_c w[t, c] · ye[slot(t, c)] — the combine as one
    inverse-permutation gather plus a per-token segment-sum over the k
    contiguous copy rows (the einsum below contracts exactly that).

    Backward re-disperses the incoming grad into the expert blocks with
    the FORWARD maps — d_ye[s] = w[token(s), choice(s)] · g[token(s)],
    again one gather — so neither direction lowers to an XLA row scatter
    (TPU row scatters serialize; gathers run near bandwidth)."""
    t, k = w.shape
    rows = jnp.take(ye, jnp.where(keep, slot_cl, 0), axis=0)
    rows = jnp.where(keep[:, None], rows, 0).reshape(t, k, -1)
    return jnp.einsum("tk,tkh->th", w, rows)


def _combine_gather_fwd(ye, w, slot_cl, keep, buf_src, hit):
    return _combine_gather(ye, w, slot_cl, keep, buf_src, hit), \
        (ye, w, slot_cl, keep, buf_src, hit)


def _combine_gather_bwd(res, g):
    ye, w, slot_cl, keep, buf_src, hit = res
    t, k = w.shape
    # d_ye: expert slot s receives its token's grad row scaled by its
    # gate weight — a gather over the forward copy→slot map
    src = jnp.where(hit, buf_src, 0)
    w_slot = jnp.where(hit, jnp.take(w.reshape(-1), src), 0)
    d_ye = (jnp.take(g, src // k, axis=0)
            * w_slot[:, None]).astype(ye.dtype)
    # d_w recomputes the gathered rows (cheap vs carrying (t·k, h))
    rows = jnp.take(ye, jnp.where(keep, slot_cl, 0), axis=0)
    rows = jnp.where(keep[:, None], rows, 0).reshape(t, k, -1)
    dw = jnp.einsum("th,tkh->tk", g, rows).astype(w.dtype)
    return d_ye, dw, _f0(slot_cl), _f0(keep), _f0(buf_src), _f0(hit)


_combine_gather.defvjp(_combine_gather_fwd, _combine_gather_bwd)


def topk_routing(logits, k: int, capacity: int, normalize_topk: bool = True):
    """GShard-style top-k routing with static capacity — compact form.

    logits: (tokens, E) fp32. Returns (gate_idx (T, k) int, gate_vals
    (T, k) fp32, pos (T, k) int — the token's slot in its expert's queue,
    keep (T, k) bool, aux_loss scalar, stats dict). Choice 0 for all tokens
    claims capacity before choice 1 (reference GShardGate priority
    semantics). The compact form is O(T·k); the (T, E, C) one-hot tensors
    of `topk_gating` are derived views for callers that want them.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)          # (T, k)
    if normalize_topk:
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # load-balance aux (Switch/GShard): E * Σ_e mean_prob_e · frac_routed_e
    me = jnp.mean(probs, axis=0)                           # (E,)
    ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32),
                  axis=0)                                  # (E,)
    aux = e * jnp.sum(me * ce)

    # position in each expert's queue, choices processed in priority order:
    # flatten (k, T) so all choice-0 tokens precede choice-1 tokens
    mask = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)     # (T, k, E)
    mask_kt = jnp.swapaxes(mask, 0, 1).reshape(k * t, e)    # (k*T, E)
    pos_kt = jnp.cumsum(mask_kt, axis=0) - mask_kt          # claimed before me
    pos = jnp.swapaxes(pos_kt.reshape(k, t, e), 0, 1)       # (T, k, E)
    pos = jnp.sum(pos * mask, axis=-1)                      # (T, k)
    routed = gate_vals > 0.0
    keep = (pos < capacity) & routed                        # (T, k)

    load = jnp.sum(mask, axis=(0, 1)).astype(jnp.float32)   # (E,) tokens/exp
    n_routed = jnp.maximum(jnp.sum(routed.astype(jnp.float32)), 1.0)
    stats = {
        "moe_dropped_fraction":
            jnp.sum((routed & ~keep).astype(jnp.float32)) / n_routed,
        "moe_expert_load": load / jnp.maximum(jnp.sum(load), 1.0),
        "moe_capacity": jnp.asarray(float(capacity)),
        "moe_max_load_over_capacity": jnp.max(load) / float(capacity),
    }
    return gate_idx, gate_vals, pos, keep, aux, stats


def topk_gating(logits, k: int, capacity: int, normalize_topk: bool = True):
    """(T, E, C) one-hot view of `topk_routing` (legacy/einsum dispatch).

    Returns (combine (T, E, C), dispatch bool (T, E, C), aux_loss).
    """
    t, e = logits.shape
    gate_idx, gate_vals, pos, keep, aux, _ = topk_routing(
        logits, k, capacity, normalize_topk)
    mask = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)       # (T, k, E)
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)   # (T, k, C)
    contrib = (gate_vals * keep)[..., None] * pos_oh            # (T, k, C)
    combine = jnp.einsum("tkc,tke->tec", contrib, mask)
    dispatch = combine > 0.0
    return combine, dispatch, aux


class GShardGate(Layer):
    """Top-2 gate (reference: moe/gate/gshard_gate.py)."""

    top_k = 2

    def __init__(self, hidden_size, num_experts, capacity_factor=1.25):
        super().__init__()
        self.proj = _GateProj(hidden_size, num_experts)
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor

    def capacity(self, n_tokens):
        return max(4, int(math.ceil(
            self.capacity_factor * self.top_k * n_tokens / self.num_experts)))

    def forward(self, x_tokens):
        logits = self.proj(x_tokens)
        return topk_gating(logits, self.top_k,
                           self.capacity(x_tokens.shape[0]))

    def route(self, x_tokens):
        """Compact routing: (idx, vals, pos, keep, aux, stats, capacity)."""
        logits = self.proj(x_tokens)
        cap = self.capacity(x_tokens.shape[0])
        return topk_routing(logits, self.top_k, cap) + (cap,)


class SwitchGate(GShardGate):
    """Top-1 gate (reference: moe/gate/switch_gate.py)."""

    top_k = 1


class _GateProj(Layer):
    def __init__(self, hidden_size, num_experts):
        super().__init__()
        self.weight = self.create_parameter(
            (hidden_size, num_experts),
            default_initializer=init.Normal(0.0, 0.02))

    def forward(self, x):
        # router math in fp32 (reference casts too — routing is precision-
        # sensitive)
        return jnp.matmul(x.astype(jnp.float32),
                          self.weight.astype(jnp.float32))


class GroupedSwiGLUExperts(Layer):
    """All experts' SwiGLU FFNs as three grouped (E, ·, ·) weights."""

    def __init__(self, num_experts, hidden_size, ffn_size, initializer_range=0.02,
                 ep_axes: Sequence[str] = EP_AXES, mp_axis: str = "mp",
                 dtype=None):
        super().__init__()
        w = init.Normal(0.0, initializer_range)
        e, h, f = num_experts, hidden_size, ffn_size
        self.w_gate = self.create_parameter((e, h, f), dtype=dtype,
                                            default_initializer=w)
        self.w_up = self.create_parameter((e, h, f), dtype=dtype,
                                          default_initializer=w)
        self.w_down = self.create_parameter((e, f, h), dtype=dtype,
                                            default_initializer=w)
        ep = tuple(ep_axes)
        self._parameters["w_gate"].pspec = _ep_spec(ep, 3, {2: mp_axis})
        self._parameters["w_up"].pspec = _ep_spec(ep, 3, {2: mp_axis})
        self._parameters["w_down"].pspec = _ep_spec(ep, 3, {1: mp_axis})
        self.ep_axes = ep
        self.mp_axis = mp_axis

    def forward(self, xe):
        """xe: (E, C_total, h) dispatched tokens → (E, C_total, h)."""
        spec = lambda nd: _ep_spec(self.ep_axes, nd)
        for a in self.ep_axes:
            xe = constrain(xe, spec, a)     # all_to_all into expert shards
        y = _swiglu(xe, self.w_gate, self.w_up, self.w_down)
        for a in self.ep_axes:
            y = constrain(y, spec, a)
        return y

    def forward_ragged(self, xs, group_sizes):
        """Dropless path: xs (N, h) tokens sorted by expert, group_sizes
        (E,) int32 — per-expert contiguous segment lengths. Ragged grouped
        matmuls (jax.lax.ragged_dot) instead of capacity padding; no token
        is ever dropped. Experts must be replicated across devices here
        (the capacity path is the EP-sharded one)."""
        dt = xs.dtype
        h1 = jax.lax.ragged_dot(xs, self.w_gate.astype(dt), group_sizes)
        h2 = jax.lax.ragged_dot(xs, self.w_up.astype(dt), group_sizes)
        return jax.lax.ragged_dot(F.silu(h1) * h2, self.w_down.astype(dt),
                                  group_sizes)


class MoELayer(Layer):
    """Token-choice MoE block: gate → all_to_all dispatch → grouped experts
    → combine. Returns (output, aux_loss).

    Reference parity: paddle.incubate.distributed.models.moe.MoELayer
    (gate=GShard top-2 or Switch top-1, capacity dropping, aux loss).
    """

    def __init__(self, hidden_size, ffn_size, num_experts, top_k=None,
                 capacity_factor=1.25, gate: str = "gshard",
                 initializer_range=0.02, ep_axes: Sequence[str] = EP_AXES,
                 mp_axis: str = "mp", dtype=None, dropless: bool = False,
                 dispatch_mode: str = "scatter"):
        super().__init__()
        gate_cls = {"gshard": GShardGate, "switch": SwitchGate}[gate]
        if gate == "switch" and top_k not in (None, 1):
            raise ValueError(f"gate='switch' is top-1 routing; got top_k={top_k}")
        if dispatch_mode not in ("scatter", "sort", "fused", "einsum",
                                 "alltoall"):
            raise ValueError(f"unknown dispatch_mode {dispatch_mode!r}")
        self.gate = gate_cls(hidden_size, num_experts,
                             capacity_factor=capacity_factor)
        if top_k is not None:
            self.gate.top_k = top_k
        # dropless replicates experts (ragged segments don't EP-shard)
        self.experts = GroupedSwiGLUExperts(
            num_experts, hidden_size, ffn_size,
            initializer_range=initializer_range,
            ep_axes=() if dropless else ep_axes,
            mp_axis=mp_axis, dtype=dtype)
        self.num_experts = num_experts
        self.hidden_size = hidden_size
        self.dropless = dropless
        self.dispatch_mode = dispatch_mode

    def _forward_capacity(self, xt, dtype):
        """Scatter dispatch: O(T·k) index ops instead of the O(T·E·C)
        one-hot einsums (the global_scatter/gather mechanism cost parity —
        SURVEY.md §2.6-EP)."""
        e = self.num_experts
        idx, vals, pos, keep, aux, stats, cap = self.gate.route(xt)
        buf, slot = _slot_scatter(xt.astype(dtype), idx, pos, keep, cap, e)
        ye = self.experts(buf.reshape(e, cap, -1)).reshape(e * cap, -1)
        yt = _slot_combine(ye, slot, vals, keep, dtype)
        return yt, aux, stats

    def _forward_sort(self, xt, dtype):
        """Permutation dispatch: one cheap int32 SCALAR scatter builds the
        inverse copy→slot map (_perm_maps), then dispatch and combine run
        as row gathers in forward AND backward (custom-VJP
        inverse-permutation) — no ROW scatter anywhere. TPU row-scatters
        serialize; gathers run near bandwidth. Kept as the A/B baseline
        for 'fused', which removes this path's (t·k, h) copy
        materialization and one permutation pass per direction."""
        e = self.num_experts
        t, h = xt.shape
        idx, vals, pos, keep, aux, stats, cap = self.gate.route(xt)
        k = idx.shape[1]
        keep_f = keep.reshape(-1)
        slot = _slots(idx, pos, keep, cap, e)
        slot_cl = jnp.clip(slot, 0, e * cap - 1)
        buf_src, hit = _perm_maps(slot, e, cap, t * k)
        xt_k = _token_copies(xt.astype(dtype), k)
        buf = _permute_rows(xt_k, buf_src, hit, slot_cl, keep_f)
        ye = self.experts(buf.reshape(e, cap, h)).reshape(e * cap, h)
        gathered = _permute_rows(ye, slot_cl, keep_f, buf_src, hit)
        w = (vals * keep).astype(dtype)
        yt = jnp.einsum("tk,tkh->th", w, gathered.reshape(t, k, h))
        return yt, aux, stats

    def _forward_fused(self, xt, dtype):
        """Fused permutation dispatch — the r5 dispatch-residual redesign.

        'sort' runs FOUR row passes per direction (materialize the
        (t·k, h) token copies, permute them into the expert buffer;
        permute the outputs back, weighted-sum them). Here the dispatch
        permutation is fused with the expert matmul input staging: the
        (E, cap, h) blocks are gathered DIRECTLY from the (t, h) token
        rows (token index recovered from the inverse copy→slot map inside
        the gather), and the combine is one inverse gather + per-token
        segment-sum with the gate weights. Two row passes per direction,
        no (t·k, h) intermediate, still zero row scatters (custom VJPs
        mirror each gather with its inverse)."""
        e = self.num_experts
        t, h = xt.shape
        idx, vals, pos, keep, aux, stats, cap = self.gate.route(xt)
        k = idx.shape[1]
        keep_f = keep.reshape(-1)
        slot = _slots(idx, pos, keep, cap, e)
        slot_cl = jnp.clip(slot, 0, e * cap - 1)
        buf_src, hit = _perm_maps(slot, e, cap, t * k)
        buf = _gather_dispatch(xt.astype(dtype), buf_src, hit, slot_cl,
                               keep_f, k)
        ye = self.experts(buf.reshape(e, cap, h)).reshape(e * cap, h)
        w = (vals * keep).astype(dtype)
        yt = _combine_gather(ye, w, slot_cl, keep_f, buf_src, hit)
        return yt, aux, stats

    def _forward_einsum(self, xt, dtype):
        """Legacy (T, E, C) one-hot dispatch — kept for A/B comparison."""
        combine, dispatch, aux = self.gate(xt)            # (T, E, C)
        xe = jnp.einsum("tec,th->ech", dispatch.astype(dtype), xt)
        ye = self.experts(xe)                             # (E, C, h)
        yt = jnp.einsum("tec,ech->th", combine.astype(dtype), ye)
        return yt, aux, None

    def _forward_alltoall(self, xt, dtype):
        """Explicit lax.all_to_all dispatch over the EP axis inside a
        shard_map — the literal global_scatter/global_gather mechanism
        (SURVEY.md §2.6-EP, collective/global_scatter_op.cu): each device
        routes its token shard, exchanges fixed-capacity per-destination
        buffers with an all_to_all, runs its local experts, and reverses
        the exchange to combine.

        Requires an active hybrid mesh whose `ep_axes` product divides
        num_experts; tokens must be shardable over that axis. Composes
        with mp_degree > 1: each expert's FFN is column/row-sharded over
        the mp axis inside the same shard_map (psum on the down-proj).

        CPU-sim caveat: XLA:CPU runs one thread per simulated device with
        a 40 s collective-rendezvous timeout; on a single-core host, long
        uninterrupted loops over this program can starve a participant and
        abort (rendezvous.cc "Termination timeout"). Real multi-chip
        executions are unaffected."""
        from jax import shard_map

        from paddle_tpu.parallel.topology import (
            get_hybrid_communicate_group)

        hcg = get_hybrid_communicate_group()
        if hcg is None:
            raise RuntimeError(
                "dispatch_mode='alltoall' needs fleet.init (an active "
                "hybrid mesh); use 'scatter' for single-mesh-free runs")
        mesh = hcg.mesh
        ep = self.experts.ep_axes
        if not ep:
            raise ValueError(
                "dispatch_mode='alltoall' needs ep_axes (experts replicated "
                "with ep_axes=() have no axis to exchange over — use "
                "'scatter' or 'sort')")
        # multiple EP axes act as ONE flattened axis (row-major over the
        # tuple — the same convention shard_map uses for a dim sharded
        # over an axis tuple, so the exchange and the sharding agree)
        axis = ep if len(ep) > 1 else ep[0]
        mp_axis = self.experts.mp_axis
        mp_deg = mesh.shape.get(mp_axis, 1)
        pdim = 1
        for a in ep:
            pdim *= mesh.shape[a]
        e = self.num_experts
        if e % pdim or xt.shape[0] % pdim:
            raise ValueError(
                f"the EP axes {ep} (size {pdim}) must divide both "
                f"num_experts {e} and the token count {xt.shape[0]}")
        e_loc = e // pdim
        gate_w = self.gate.proj.weight
        wg, wu, wd = (self.experts.w_gate, self.experts.w_up,
                      self.experts.w_down)
        cap = self.gate.capacity(xt.shape[0] // pdim)
        top_k = self.gate.top_k

        def body(xt_loc, gate_w, wg, wu, wd):
            # xt_loc (T_loc, h); expert weights sharded dim0 over the EP
            # axis and (when mp_deg > 1) the ffn dim over the mp axis —
            # each device holds a column slice of its local experts' FFNs
            # and the down-proj partial sums reduce over mp (Megatron-style
            # TP inside each expert, composed with EP alltoall)
            h = xt_loc.shape[-1]
            logits = jnp.matmul(xt_loc.astype(jnp.float32),
                                gate_w.astype(jnp.float32))
            idx, vals, pos, keep, aux, _ = topk_routing(logits, top_k, cap)
            # slot layout groups experts by owner: dest p owns experts
            # [p*e_loc, (p+1)*e_loc)
            send, slot = _slot_scatter(xt_loc, idx, pos, keep, cap, e)
            send = send.reshape(pdim, e_loc * cap, h)
            # exchange: device q's block p  →  device p's block q
            recv = jax.lax.all_to_all(send, axis, split_axis=0,
                                      concat_axis=0, tiled=False)
            # recv (pdim_src, e_loc*cap, h) → (e_loc, pdim_src*cap, h)
            xe = recv.reshape(pdim, e_loc, cap, h).transpose(1, 0, 2, 3) \
                .reshape(e_loc, pdim * cap, h)
            ye = _swiglu(xe, wg, wu, wd)
            if mp_deg > 1:      # reduce the ffn-sharded contraction
                ye = jax.lax.psum(ye, mp_axis)
            # reverse exchange
            back = ye.reshape(e_loc, pdim, cap, h).transpose(1, 0, 2, 3) \
                .reshape(pdim, e_loc * cap, h)
            got = jax.lax.all_to_all(back, axis, split_axis=0,
                                     concat_axis=0, tiled=False)
            yt = _slot_combine(got.reshape(e * cap, h), slot, vals, keep,
                               xt_loc.dtype)
            # aux is a per-shard mean over local tokens; average over shards
            return yt, jax.lax.pmean(aux, axis)

        mp_s = mp_axis if mp_deg > 1 else None
        yt, aux = shard_map(
            body, mesh=mesh,
            in_specs=(P(axis), P(),
                      P(axis, None, mp_s),     # w_gate (E, h, f)
                      P(axis, None, mp_s),     # w_up
                      P(axis, mp_s, None)),    # w_down (E, f, h)
            out_specs=(P(axis), P()),
            check_vma=False)(xt, gate_w, wg, wu, wd)
        return yt.astype(dtype), aux, None

    def _forward_dropless(self, xt, dtype):
        """Sort + ragged grouped matmul: every routed token is computed
        (MegaBlocks-style dropless, the expert-choice/dropless gap noted in
        STATUS.md)."""
        e = self.num_experts
        idx, vals, pos, keep, aux, stats, _ = self.gate.route(xt)
        t, k = idx.shape
        h = xt.shape[-1]
        e_flat = idx.reshape(-1)                          # (T·k,)
        order = jnp.argsort(e_flat, stable=True)
        xt_k = jnp.broadcast_to(xt[:, None], (t, k, h)).reshape(t * k, h)
        xs = jnp.take(xt_k, order, axis=0)
        group_sizes = jnp.bincount(e_flat, length=e).astype(jnp.int32)
        ys = self.experts.forward_ragged(xs, group_sizes)
        inv = jnp.argsort(order, stable=True)
        ys = jnp.take(ys, inv, axis=0).reshape(t, k, h)
        w = vals.astype(dtype)                            # no capacity drop
        yt = jnp.einsum("tk,tkh->th", w, ys)
        stats = dict(stats)
        stats["moe_dropped_fraction"] = jnp.zeros(())
        return yt, aux, stats

    def forward(self, x, return_stats: bool = False):
        b, s, h = x.shape
        xt = x.reshape(b * s, h)
        if self.dropless:
            yt, aux, stats = self._forward_dropless(xt, x.dtype)
        elif self.dispatch_mode == "scatter":
            yt, aux, stats = self._forward_capacity(xt, x.dtype)
        elif self.dispatch_mode == "sort":
            yt, aux, stats = self._forward_sort(xt, x.dtype)
        elif self.dispatch_mode == "fused":
            yt, aux, stats = self._forward_fused(xt, x.dtype)
        elif self.dispatch_mode == "alltoall":
            yt, aux, stats = self._forward_alltoall(xt, x.dtype)
        else:
            yt, aux, stats = self._forward_einsum(xt, x.dtype)
        out = yt.reshape(b, s, h)
        if return_stats:
            return out, aux, stats
        return out, aux
