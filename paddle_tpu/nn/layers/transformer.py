"""Transformer layers (ref: python/paddle/nn/layer/transformer.py).

MultiHeadAttention uses the (batch, seq, heads, head_dim) internal layout and
dispatches to the flash-attention path in `paddle_tpu.ops` — the TPU stand-in
for the reference's fused_attention/flash_attn phi kernels.
"""

import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layers.common import Linear, Dropout, LayerList
from paddle_tpu.nn.layers.norm import LayerNorm


class MultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr)
        self.k_proj = Linear(kdim, embed_dim, bias_attr=bias_attr)
        self.v_proj = Linear(vdim, embed_dim, bias_attr=bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        b, sq = query.shape[0], query.shape[1]
        sk = key.shape[1]
        q = self.q_proj(query).reshape(b, sq, self.num_heads, self.head_dim)
        k = self.k_proj(key).reshape(b, sk, self.num_heads, self.head_dim)
        v = self.v_proj(value).reshape(b, sk, self.num_heads, self.head_dim)
        if cache is not None:
            k = jnp.concatenate([cache[0], k], axis=1)
            v = jnp.concatenate([cache[1], v], axis=1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        out = out.reshape(b, sq, self.embed_dim)
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False):
        super().__init__()
        self.self_attn = MultiHeadAttention(d_model, nhead,
                                            dropout=attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = {"relu": F.relu, "gelu": F.gelu}[activation]
        self.normalize_before = normalize_before

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = residual + self.dropout1(self.self_attn(src, attn_mask=src_mask))
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout_act(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        if isinstance(encoder_layer, Layer):
            # reference semantics: independent per-depth parameter copies
            layers = [encoder_layer] + [copy.deepcopy(encoder_layer)
                                        for _ in range(num_layers - 1)]
        else:  # factory callable
            layers = [encoder_layer() for _ in range(num_layers)]
        self.layers = LayerList(layers)
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None and "norm" in self._sub_layers:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    """Self-attn (causal) + cross-attn + FFN (reference:
    nn.TransformerDecoderLayer)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False):
        super().__init__()
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None
                                   else dropout)
        self.activation = {"relu": F.relu, "gelu": F.gelu}[activation]
        self.normalize_before = normalize_before

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            attn_out = self.self_attn(tgt, attn_mask=tgt_mask)
        else:
            attn_out, cache = self.self_attn(tgt, attn_mask=tgt_mask,
                                             cache=cache)
        tgt = residual + self.dropout1(attn_out)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = residual + self.dropout2(
            self.cross_attn(tgt, memory, memory, attn_mask=memory_mask))
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout_act(self.activation(
            self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is not None:
            return tgt, cache
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        if isinstance(decoder_layer, Layer):
            layers = [decoder_layer] + [copy.deepcopy(decoder_layer)
                                        for _ in range(num_layers - 1)]
        else:
            layers = [decoder_layer() for _ in range(num_layers)]
        self.layers = LayerList(layers)
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask=tgt_mask,
                        memory_mask=memory_mask)
        if self.norm is not None and "norm" in self._sub_layers:
            out = self.norm(out)
        return out


class Transformer(Layer):
    """Full encoder-decoder (reference: paddle.nn.Transformer)."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False):
        super().__init__()
        self.encoder = TransformerEncoder(
            lambda: TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before),
            num_encoder_layers,
            norm=LayerNorm(d_model) if normalize_before else None)
        self.decoder = TransformerDecoder(
            lambda: TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before),
            num_decoder_layers,
            norm=LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        """Additive causal mask (0 on/below diag, -inf above)."""
        mask = jnp.triu(jnp.full((length, length), -jnp.inf), k=1)
        return mask
