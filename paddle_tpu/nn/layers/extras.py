"""Layer breadth: padding, pixel ops, dropout variants, distance, vision
pooling/conv variants, instance norms (reference: python/paddle/nn/layer/
{common,pooling,conv,norm,distance,vision}.py)."""

import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn.layers.norm import BatchNorm2D


# ---- padding ----------------------------------------------------------------

class _PadNd(Layer):
    nd = 2
    _channels_last = ("NLC", "NHWC", "NDHWC")

    def __init__(self, padding, mode="constant", value=0.0,
                 data_format=None):
        super().__init__()
        self.padding = ([padding] * (2 * self.nd)
                        if isinstance(padding, int) else list(padding))
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        # paddle pad order: (left, right[, top, bottom[, front, back]]) —
        # pairs apply from the LAST spatial dim backwards. Spatial dims are
        # trailing for channels-first, but 1..nd for channels-last.
        pairs = [(self.padding[2 * i], self.padding[2 * i + 1])
                 for i in range(len(self.padding) // 2)]
        cl = self.data_format in self._channels_last
        cfg = [(0, 0)] * x.ndim
        for i, pr in enumerate(pairs):
            axis = (self.nd - i) if cl else (x.ndim - 1 - i)
            cfg[axis] = pr
        flat = [v for pr in cfg for v in pr]
        return F.pad(x, flat, mode=self.mode, value=self.value)


class Pad1D(_PadNd):
    nd = 1


class Pad2D(_PadNd):
    nd = 2


class Pad3D(_PadNd):
    nd = 3


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW"):
        super().__init__(padding, mode="constant", value=0.0,
                         data_format=data_format)


class ZeroPad1D(Pad1D):
    """Reference paddle.nn.ZeroPad1D."""

    def __init__(self, padding, data_format="NCL"):
        super().__init__(padding, mode="constant", value=0.0,
                         data_format=data_format)


class ZeroPad3D(Pad3D):
    """Reference paddle.nn.ZeroPad3D."""

    def __init__(self, padding, data_format="NCDHW"):
        super().__init__(padding, mode="constant", value=0.0,
                         data_format=data_format)


# ---- pixel / channel rearrangement -----------------------------------------

class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.r = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.r, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW"):
        super().__init__()
        self.r = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.r, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW"):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


# ---- dropout variants -------------------------------------------------------

class Dropout2D(Layer):
    """Drops whole channels (reference: spatial dropout)."""

    def __init__(self, p=0.5, data_format="NCHW"):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        import jax
        from paddle_tpu.core import rng as _rng
        n, c = (x.shape[0], x.shape[1]) if self.data_format == "NCHW" \
            else (x.shape[0], x.shape[-1])
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(_rng.next_rng_key("dropout2d"), keep,
                                    (n, c))
        shape = (n, c, 1, 1) if self.data_format == "NCHW" else (n, 1, 1, c)
        return jnp.where(mask.reshape(shape), x / keep, 0.0).astype(x.dtype)


class Dropout3D(Dropout2D):
    def __init__(self, p=0.5, data_format="NCDHW"):
        super().__init__(p)
        self.data_format = data_format

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        import jax
        from paddle_tpu.core import rng as _rng
        ch_last = self.data_format == "NDHWC"
        n = x.shape[0]
        c = x.shape[-1] if ch_last else x.shape[1]
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(_rng.next_rng_key("dropout3d"), keep,
                                    (n, c))
        shape = (n, 1, 1, 1, c) if ch_last else (n, c, 1, 1, 1)
        return jnp.where(mask.reshape(shape), x / keep, 0.0).astype(x.dtype)


class AlphaDropout(Layer):
    """SELU-preserving dropout (reference alpha_dropout)."""

    _alpha_p = -1.7580993408473766  # -scale * alpha of SELU

    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def _mask_shape(self, x):
        return x.shape

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        import jax
        from paddle_tpu.core import rng as _rng
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(_rng.next_rng_key("alpha_dropout"), keep,
                                    self._mask_shape(x))
        a = (keep + self.p * self._alpha_p ** 2 * keep) ** -0.5
        b = -a * self._alpha_p * self.p
        y = jnp.where(mask, x, jnp.asarray(self._alpha_p, x.dtype))
        return (a * y + b).astype(x.dtype)


class FeatureAlphaDropout(AlphaDropout):
    """Alpha dropout over whole channel maps (reference
    paddle.nn.FeatureAlphaDropout): the SELU-preserving affine is applied
    with one mask element per (sample, channel), channels-first."""

    def _mask_shape(self, x):
        return x.shape[:2] + (1,) * (x.ndim - 2)


# ---- distance ---------------------------------------------------------------

class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


# ---- bilinear ---------------------------------------------------------------

class Bilinear(Layer):
    """out_k = x1ᵀ W_k x2 + b_k (reference paddle.nn.Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features, dtype=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), dtype=dtype,
            default_initializer=init.XavierUniform())
        self.bias = self.create_parameter(
            (out_features,), dtype=dtype,
            default_initializer=init.Constant(0.0), is_bias=True)

    def forward(self, x1, x2):
        return jnp.einsum("bi,oij,bj->bo", x1, self.weight, x2) + self.bias


# ---- conv / pool variants ---------------------------------------------------

class Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None, dtype=None,
                 data_format="NCDHW"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size,) * 3
        fan_in = in_channels // groups * k[0] * k[1] * k[2]
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + tuple(k), dtype=dtype,
            default_initializer=init.KaimingUniform(fan_in=fan_in))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), dtype=dtype,
            default_initializer=init.Constant(0.0), is_bias=True)
        self.stride, self.padding = stride, padding
        self.dilation, self.groups = dilation, groups
        self.data_format = data_format

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, bias_attr=None, dtype=None):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size,) * 3
        self.weight = self.create_parameter(
            (in_channels, out_channels) + tuple(k), dtype=dtype,
            default_initializer=init.KaimingUniform(
                fan_in=in_channels * k[0] * k[1] * k[2]))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), dtype=dtype,
            default_initializer=init.Constant(0.0), is_bias=True)
        self.stride, self.padding = stride, padding
        self.output_padding = output_padding

    def forward(self, x):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.args = (kernel_size, stride, padding)

    def forward(self, x):
        return F.max_pool1d(x, *self.args)


class AvgPool1D(MaxPool1D):
    def forward(self, x):
        return F.avg_pool1d(x, *self.args)


class MaxPool3D(MaxPool1D):
    def forward(self, x):
        return F.max_pool3d(x, *self.args)


class AvgPool3D(MaxPool1D):
    def forward(self, x):
        return F.avg_pool3d(x, *self.args)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveAvgPool1D(AdaptiveMaxPool2D):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(AdaptiveMaxPool2D):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__()
        self.size, self.scale = size, scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale,
                             mode="nearest", data_format=self.data_format)


class UpsamplingBilinear2D(UpsamplingNearest2D):
    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale,
                             mode="bilinear", data_format=self.data_format)


# ---- norm variants ----------------------------------------------------------

class BatchNorm1D(BatchNorm2D):
    """(N, C) or (N, C, L) inputs — same running-stat machinery."""


class BatchNorm3D(BatchNorm2D):
    """(N, C, D, H, W) inputs."""


class SyncBatchNorm(BatchNorm2D):
    """Cross-replica BN. Under GSPMD the batch axis is sharded and XLA
    computes global reductions automatically when stats are replicated —
    the veneer exists for API parity (reference: nn.SyncBatchNorm over
    NCCL all_reduce of partial sums).

    convert_sync_batchnorm upgrades BatchNorm* layers in-place."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, BatchNorm2D) and not isinstance(
                    sub, SyncBatchNorm):
                new = SyncBatchNorm(sub.num_features, sub.momentum,
                                    sub.epsilon,
                                    data_format=sub.data_format)
                new._parameters = sub._parameters
                new._buffers = sub._buffers
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class _InstanceNormNd(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), default_initializer=init.Constant(1.0))
            self.bias = self.create_parameter(
                (num_features,), default_initializer=init.Constant(0.0),
                is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self.epsilon)


class InstanceNorm1D(_InstanceNormNd):
    pass


class InstanceNorm2D(_InstanceNormNd):
    pass


class InstanceNorm3D(_InstanceNormNd):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW"):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class Unflatten(Layer):
    def __init__(self, axis, shape):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from paddle_tpu.tensor import unflatten
        return unflatten(x, self.axis, self.shape)
