"""Common layers: Linear, Embedding, Dropout, activations, containers.

Ref: python/paddle/nn/layer/{common.py,container.py,activation.py}.
"""

from collections import OrderedDict

import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer, Parameter
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.core.dtype import to_jax_dtype


class Linear(Layer):
    """y = xW + b with W of shape (in_features, out_features) (reference layout)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None, dtype=None):
        super().__init__()
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) else init.XavierNormal()
        self.weight = self.create_parameter(
            (in_features, out_features), dtype=dtype, default_initializer=w_init)
        if bias_attr is not False:
            b_init = bias_attr if isinstance(bias_attr, init.Initializer) else init.Constant(0.0)
            self.bias = self.create_parameter(
                (out_features,), dtype=dtype, default_initializer=b_init, is_bias=True)
        else:
            self.bias = None
        self.in_features, self.out_features = in_features, out_features

    def forward(self, x):
        return F.linear(x, self.weight, self.bias if "bias" in self._parameters else None)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None, dtype=None):
        super().__init__()
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) else init.Normal(0.0, 1.0)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), dtype=dtype, default_initializer=w_init)
        self.padding_idx = padding_idx
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim

    def forward(self, x):
        return F.embedding(x, self.weight, self.padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, mode="upscale_in_train", name=None, rng_name="dropout"):
        super().__init__()
        self.p = p
        self.mode = mode
        self.rng_name = rng_name

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode,
                         rng_name=self.rng_name)


class Identity(Layer):
    def forward(self, x):
        return x


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from paddle_tpu import tensor as T
        return T.flatten(x, self.start_axis, self.stop_axis)


class _Activation(Layer):
    _fn = None

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return type(self)._fn(x)


class ReLU(_Activation):
    _fn = staticmethod(F.relu)


class ReLU6(_Activation):
    _fn = staticmethod(F.relu6)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self.approximate)


class Silu(_Activation):
    _fn = staticmethod(F.silu)


class Sigmoid(_Activation):
    _fn = staticmethod(F.sigmoid)


class Tanh(_Activation):
    _fn = staticmethod(F.tanh)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class Hardswish(_Activation):
    _fn = staticmethod(F.hardswish)


class Mish(_Activation):
    _fn = staticmethod(F.mish)


# ---- containers ------------------------------------------------------------

class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, l in layers[0]:
                self.add_sublayer(str(name), l)
        else:
            for i, l in enumerate(layers):
                self.add_sublayer(str(i), l)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for name, l in (sublayers or {}).items():
            self.add_sublayer(name, l)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __len__(self):
        return len(self._sub_layers)

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, p):
        self.add_parameter(str(len(self._parameters)), p)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)].value

    def __len__(self):
        return len(self._parameters)
