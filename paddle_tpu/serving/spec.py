"""Speculative-decoding proposers for the serving engine.

Decode is memory-bandwidth-bound: every serial decode step streams the
whole model once to produce ONE token per slot. Speculation trades k
cheap *proposed* tokens per slot for one batched *verify* pass through
the fused paged kernel (`ops.fused_decode.fused_paged_verify_step`),
committing however many proposals the engine's own sampling stream
agrees with — fewer serial dispatches per generated token, bit-identical
tokens (docs/SERVING.md §Speculative decoding).

Two proposers:

* **n-gram** (self-speculative, no extra model): per-slot suffix match
  over the committed tokens (prompt + generated) — the prompt-lookup /
  "assisted generation" trick. The matcher runs ON DEVICE inside the
  verify program over a carried token-history buffer, so a steady
  speculative tick performs zero host->device transfers (the PR 9
  sanitizer invariant). Best on repetitive mixes: extraction, code,
  chat with quoting.
* **draft model** (llama-tiny drafting for llama-medium): a small model
  rides the SAME paged serving machinery — its own block tables over
  its own bf16 pool, positions shared with the target (draft and target
  appends advance in lockstep) — and proposes greedily k tokens per
  tick in one scanned program.

Acceptance is TOKEN-EXACT, not distribution-level rejection sampling: a
proposal survives only if it equals the token the engine's own
per-request RNG stream (``fold_in(seed, count)``, PR 5) would have
sampled at that position from the verify logits. Greedy collapses to
longest exact-match-of-argmax prefix; sampled draws each position's
sample from its own leave-one-out fold of the request stream. Either
way the committed tokens are bitwise the ones the non-speculative
engine emits — the parity contract tests/test_serving_spec.py pins.

Telemetry: each speculative tick records a ``serving.spec_verify``
span (proposed/accepted/committed counts) carrying the ``trace_ids``
of every active slot — a verify tick is a shared event on N causal
request chains, and the timeline export fans it out to each
(docs/OBSERVABILITY.md §Request traces).
"""

import numbers
from typing import Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["SpecConfig", "PROPOSERS", "ngram_propose",
           "ngram_propose_host"]

#: supported proposer kinds
PROPOSERS = ("ngram", "draft")


class SpecConfig:
    """Speculative-decoding config for ``ServingEngine(speculate=...)``.

    ``k`` proposals are verified per slot per tick (one fused verify
    dispatch scores k+1 tail tokens). ``proposer="ngram"`` needs no
    extra model; ``proposer="draft"`` requires ``draft_model`` — a
    fused-decode-eligible small model (llama/gpt) sharing the target's
    tokenizer/vocab. ``ngram_max``/``ngram_min`` bound the suffix
    lengths the n-gram matcher tries (longest first).

    ``adaptive=True`` arms per-slot adaptive k (docs/SERVING.md
    §Speculative decoding): each slot carries an acceptance EWMA
    (accepted/proposed per verify tick); every ``adapt_every`` spec
    ticks a slot whose EWMA sits below ``acceptance_floor`` steps its
    k down one (toward ``k_min``) and one above ``acceptance_ceiling``
    steps it back up (toward ``k``). The tick's verify tail is sized
    by the MAX k over active slots, so a replica whose whole mix has
    low acceptance stops paying the k-token verify tail — with
    ``k_min=0`` it degrades all the way to the plain per-token decode
    dispatch. Committed tokens stay bit-identical at every k
    (acceptance is exact sample-match; shorter proposals just commit
    fewer per tick). A slot parked at ``k_min=0`` proposes nothing,
    so by itself its EWMA could never observe acceptance again; the
    engine therefore PROBES parked slots — every ``adapt_every``
    parked ticks their cap is raised to one proposal for a two-tick
    window (``serving.spec_k_probes``), letting the EWMA re-observe
    and the slot climb back when the mix turns favorable.

    Everything is validated HERE with plain ``ValueError``s — a bad k
    must not surface deep inside the scheduler.
    """

    __slots__ = ("k", "proposer", "ngram_max", "ngram_min",
                 "draft_model", "draft_state", "adaptive", "k_min",
                 "acceptance_floor", "acceptance_ceiling", "adapt_every",
                 "share_embeddings")

    def __init__(self, k: int = 4, proposer: str = "ngram",
                 ngram_max: int = 3, ngram_min: int = 1,
                 draft_model=None, draft_state: Optional[dict] = None,
                 adaptive: bool = False, k_min: int = 1,
                 acceptance_floor: float = 0.35,
                 acceptance_ceiling: float = 0.65,
                 adapt_every: int = 4,
                 share_embeddings: bool = True):
        if isinstance(k, bool) or not isinstance(k, numbers.Integral) \
                or k < 1:
            raise ValueError(f"speculate k must be an int >= 1, got {k!r}")
        self.k = int(k)
        self.adaptive = bool(adaptive)
        if isinstance(k_min, bool) or not isinstance(k_min, numbers.Integral) \
                or not 0 <= k_min <= k:
            raise ValueError(
                f"k_min must be an int in [0, k={k}], got {k_min!r}")
        self.k_min = int(k_min)
        for name, v in (("acceptance_floor", acceptance_floor),
                        ("acceptance_ceiling", acceptance_ceiling)):
            if not isinstance(v, numbers.Real) or isinstance(v, bool) \
                    or not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v!r}")
        if acceptance_floor > acceptance_ceiling:
            raise ValueError(
                f"acceptance_floor {acceptance_floor} > "
                f"acceptance_ceiling {acceptance_ceiling} (the hysteresis "
                f"band would thrash k every tick)")
        self.acceptance_floor = float(acceptance_floor)
        self.acceptance_ceiling = float(acceptance_ceiling)
        if isinstance(adapt_every, bool) \
                or not isinstance(adapt_every, numbers.Integral) \
                or adapt_every < 1:
            raise ValueError(
                f"adapt_every must be an int >= 1, got {adapt_every!r}")
        self.adapt_every = int(adapt_every)
        if proposer not in PROPOSERS:
            raise ValueError(f"unknown proposer {proposer!r}; one of "
                             f"{PROPOSERS}")
        self.proposer = proposer
        for name, v in (("ngram_max", ngram_max), ("ngram_min", ngram_min)):
            if isinstance(v, bool) or not isinstance(v, numbers.Integral) \
                    or v < 1:
                raise ValueError(f"{name} must be an int >= 1, got {v!r}")
        if ngram_min > ngram_max:
            raise ValueError(f"ngram_min {ngram_min} > ngram_max "
                             f"{ngram_max}")
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)
        if proposer == "draft" and draft_model is None:
            raise ValueError(
                "proposer='draft' requires draft_model (a fused-decode-"
                "eligible small model)")
        self.draft_model = draft_model
        self.draft_state = draft_state
        # draft proposer: rebind the draft's embedding table to the
        # TARGET's array when the shapes/dtypes line up (same
        # vocab×hidden — and through tied_unembed the shared table is
        # the draft's unembedding too). One device buffer instead of
        # two; a draft with a different hidden keeps its own table,
        # silently. Bit-inert either way: equal arrays, shared or
        # copied, produce identical draft logits.
        self.share_embeddings = bool(share_embeddings)

    def to_config(self) -> dict:
        """JSON-serializable form for engine snapshots. The draft MODEL
        is not serializable — ``ServingEngine.restore`` demands it back
        as an override when the snapshot used the draft proposer."""
        return {"k": self.k, "proposer": self.proposer,
                "ngram_max": self.ngram_max, "ngram_min": self.ngram_min,
                "adaptive": self.adaptive, "k_min": self.k_min,
                "acceptance_floor": self.acceptance_floor,
                "acceptance_ceiling": self.acceptance_ceiling,
                "adapt_every": self.adapt_every,
                "share_embeddings": self.share_embeddings}


def ngram_propose(history, lengths, k: int, nmax: int, nmin: int):
    """Device-side n-gram proposal (prompt-lookup decoding), vectorized
    over slots — runs INSIDE the engine's verify program so a steady
    speculative tick stays 0-H2D.

    history (b, S) int32 — each row's committed tokens (prompt +
    generated) at indices ``[0, lengths[r])``; entries beyond are
    stale/garbage and never read. For the longest n in [nmin, nmax]
    whose length-n suffix of the committed sequence re-occurs ending
    strictly before the suffix itself, the MOST RECENT occurrence wins
    and the committed tokens that followed it become the proposal.

    Returns (proposals (b, k) int32, nprop (b,) int32) — rows with no
    match (or too-short histories) propose nothing (nprop 0, proposals
    zero-padded), which the verify pass treats as a plain decode step.
    """
    b, S = history.shape
    lengths = lengths.astype(jnp.int32)
    pos_i = jnp.arange(S, dtype=jnp.int32)[None]      # match END index i
    Lm1 = lengths[:, None] - 1                        # suffix end index
    best_idx = jnp.full((b,), -1, jnp.int32)
    best_n = jnp.zeros((b,), jnp.int32)
    for n in range(nmax, nmin - 1, -1):               # longest wins
        eq = jnp.ones((b, S), bool)
        for d in range(n):
            # history[i - d] == history[L-1 - d] — the rolled copy wraps
            # at the left edge; the pos_i >= d mask kills the wrap
            shifted = jnp.roll(history, d, axis=1)
            suf_d = jnp.take_along_axis(
                history, jnp.maximum(Lm1 - d, 0), axis=1)     # (b, 1)
            eq = eq & (shifted == suf_d) & (pos_i >= d)
        valid = eq & (pos_i >= n - 1) & (pos_i < Lm1) \
            & (Lm1 >= n)                              # suffix must exist
        idx = jnp.where(valid, pos_i, -1).max(axis=1).astype(jnp.int32)
        take = (idx >= 0) & (best_n == 0)
        best_idx = jnp.where(take, idx, best_idx)
        best_n = jnp.where(take, n, best_n)
    start = best_idx + 1
    gidx = jnp.clip(start[:, None] + jnp.arange(k, dtype=jnp.int32)[None],
                    0, S - 1)
    props = jnp.take_along_axis(history, gidx, axis=1)
    nprop = jnp.where(best_idx >= 0,
                      jnp.clip(lengths - start, 0, k), 0).astype(jnp.int32)
    props = jnp.where(jnp.arange(k)[None] < nprop[:, None], props, 0)
    return props.astype(jnp.int32), nprop


def ngram_propose_host(tokens, k: int, nmax: int, nmin: int):
    """Plain-python twin of :func:`ngram_propose` for one sequence —
    the readable specification the device matcher is tested against."""
    toks = [int(t) for t in tokens]
    L = len(toks)
    for n in range(nmax, nmin - 1, -1):
        if L - 1 < n:
            continue
        suffix = toks[L - n:]
        best = -1
        for i in range(n - 1, L - 1):                 # match END index
            if toks[i - n + 1:i + 1] == suffix:
                best = i                              # most recent wins
        if best >= 0:
            props = toks[best + 1:best + 1 + k]
            # tpu-lint: allow(host-sync): host python-list test twin
            return (np.asarray(props + [0] * (k - len(props)), np.int32),
                    len(props))
    return np.zeros(k, np.int32), 0
