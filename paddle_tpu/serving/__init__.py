"""Continuous-batching serving engine (docs/SERVING.md).

The production analog of the reference's fused_multi_transformer serving
stack: a paged KV-cache pool with per-slot block tables over the fused
decode kernel, in-flight request join/leave at slot granularity, and
content-hashed prefix reuse. ``inference.generate`` remains the
single-batch entry point; this package is the multi-request scheduler on
top of the same kernel (Orca continuous batching + vLLM paged KV, both
in the PAPERS lineage).

    from paddle_tpu import serving
    eng = serving.ServingEngine(model, max_slots=8, eos_token_id=2)
    rid = eng.submit(serving.Request(prompt_ids, max_new_tokens=64))
    eng.drain()
    out = eng.results[rid].ids        # == generate()'s output row
"""

from paddle_tpu.serving.engine import (  # noqa: F401
    ENGINE_SNAPSHOT_SCHEMA, PRIORITIES, DrainTimeout, Rejected, Request,
    RequestResult, RestoreError, ServingEngine)
from paddle_tpu.serving.layout import ServingLayout  # noqa: F401
from paddle_tpu.serving.pool import (  # noqa: F401
    SCRATCH_BLOCK, BlockPool, PoolExhausted, PrefixCache, PrefixEntry)
from paddle_tpu.serving.router import (  # noqa: F401
    REPLICA_ROLES, REPLICA_STATES, ROUTER_JOURNAL_SCHEMA, ReplicaRole,
    Router, RouterJournal)
from paddle_tpu.serving.spec import (  # noqa: F401
    PROPOSERS, SpecConfig)
from paddle_tpu.serving.transport import (  # noqa: F401
    PROTOCOL_VERSION, RemoteError, TransportClosed, TransportCorruption,
    TransportError, TransportTimeout)
from paddle_tpu.serving.worker import ReplicaProxy  # noqa: F401

__all__ = [
    "Request", "RequestResult", "ServingEngine", "ServingLayout",
    "SpecConfig",
    "PROPOSERS", "BlockPool", "PoolExhausted", "PrefixCache",
    "PrefixEntry", "SCRATCH_BLOCK", "Rejected", "RestoreError",
    "PRIORITIES", "ENGINE_SNAPSHOT_SCHEMA", "Router", "RouterJournal",
    "ROUTER_JOURNAL_SCHEMA", "REPLICA_STATES", "REPLICA_ROLES",
    "ReplicaRole", "DrainTimeout", "ReplicaProxy", "PROTOCOL_VERSION",
    "TransportError", "TransportClosed", "TransportCorruption",
    "TransportTimeout", "RemoteError",
]
