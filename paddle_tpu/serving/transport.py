"""Length-prefixed, CRC-framed, versioned RPC transport for the
cross-process serving tier (docs/SERVING.md §Cross-process tier).

The journal's framing discipline (`serving/journal.py`: every line
carries ``crc32(payload)`` and a reader that REJECTS rather than
guesses) reused on the wire.  A frame is::

    +-------+---------+-------+----------+---------+----------------+
    | magic | version | flags | length   | crc32   | payload (JSON) |
    | 4s    | u16     | u16   | u32      | u32     | `length` bytes |
    +-------+---------+-------+----------+---------+----------------+

big-endian, over a ``multiprocessing`` spawn-context pipe (one frame
per ``send_bytes`` message, so the length field is a CONSISTENCY check
against the kernel's own message framing, not a stream delimiter — a
bit-flipped payload fails the CRC without desynchronizing the
connection).  Everything in the payload is compact sorted-key JSON:
the protocol stays greppable in a pipe dump and versionable without a
schema compiler.

Failure taxonomy — typed so the proxy's retry policy can distinguish
"ask again" from "the worker is gone":

* :class:`TransportCorruption` — bad magic / unsupported version /
  length mismatch / CRC mismatch.  The frame is dropped; the caller
  may retry (idempotent ops) because the fault sites fire BEFORE any
  state mutates.
* :class:`TransportTimeout` — the peer did not answer inside the
  wall-clock deadline.  Distinguishes a HUNG worker from a dead one;
  the message contains "timed out" so `retry.is_timeout` matches.
* :class:`TransportClosed` — EOF / broken pipe: the peer process is
  gone.  Never retried; feeds the router's healthy→suspect→dead
  machine.

Fault sites ``transport.send`` / ``transport.recv`` (registered in
`resilience.faults.KNOWN_SITES`) fire BEFORE the write / read, so a
raising fault never leaves a half-written frame and never consumes the
queued one — the retry observes the same world a real transient would
leave behind.
"""

import base64
import json
import struct
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from paddle_tpu.resilience import faults as _faults

__all__ = [
    "MAGIC", "PROTOCOL_VERSION", "Channel", "RemoteError",
    "TransportClosed", "TransportCorruption", "TransportError",
    "TransportTimeout", "decode_block_entries", "decode_frame",
    "decode_request", "decode_result", "encode_block_entries",
    "encode_error", "encode_frame", "encode_request", "encode_result",
    "raise_remote",
]

MAGIC = b"PTRW"                 # Paddle_Tpu Replica Worker
PROTOCOL_VERSION = 1
_HEADER = struct.Struct(">4sHHII")   # magic, version, flags, length, crc32


class TransportError(RuntimeError):
    """Base of every transport-layer failure (never a remote app error)."""


class TransportClosed(TransportError):
    """The peer is gone — EOF, broken pipe, or an already-closed channel."""


class TransportTimeout(TransportError):
    """No reply inside the wall-clock deadline (a hung — not dead — peer)."""


class TransportCorruption(TransportError):
    """A frame failed the magic/version/length/CRC checks and was dropped."""


class RemoteError(RuntimeError):
    """A worker-side exception of a type the parent cannot reconstruct;
    the original type name and message ride in ``str(exc)``."""


def encode_frame(obj: Dict[str, Any]) -> bytes:
    payload = json.dumps(obj, separators=(",", ":"),
                         sort_keys=True).encode("utf-8")
    return _HEADER.pack(MAGIC, PROTOCOL_VERSION, 0, len(payload),
                        zlib.crc32(payload)) + payload


def decode_frame(data: bytes) -> Dict[str, Any]:
    """Parse one frame, REJECTING (never guessing) on any mismatch."""
    if len(data) < _HEADER.size:
        raise TransportCorruption(
            f"short frame: {len(data)} bytes < {_HEADER.size}-byte header")
    magic, version, _flags, length, crc = _HEADER.unpack_from(data)
    if magic != MAGIC:
        raise TransportCorruption(f"bad magic {magic!r} (want {MAGIC!r})")
    if version != PROTOCOL_VERSION:
        raise TransportCorruption(
            f"protocol version {version} (this side speaks "
            f"{PROTOCOL_VERSION})")
    payload = data[_HEADER.size:]
    if len(payload) != length:
        raise TransportCorruption(
            f"length mismatch: header says {length}, got {len(payload)}")
    if zlib.crc32(payload) != crc:
        raise TransportCorruption("payload CRC mismatch (torn frame)")
    try:
        return json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise TransportCorruption(
            f"CRC-valid frame holds non-JSON payload: {e}") from e


class Channel:
    """One framed endpoint over a ``multiprocessing.connection``
    Connection.  Symmetric — both the router-side proxy and the worker
    loop speak through it.  NOT thread-safe: the tier's single-client
    discipline (only the Router talks to a worker, one RPC in flight)
    is what makes reply matching and the piggybacked status exact."""

    def __init__(self, conn):
        self._conn = conn
        self._closed = False
        from paddle_tpu.observability import registry
        r = registry()
        self._c_sent = r.counter("serving.transport.frames", dir="send")
        self._c_recv = r.counter("serving.transport.frames", dir="recv")
        self._b_sent = r.counter("serving.transport.bytes", dir="send")
        self._b_recv = r.counter("serving.transport.bytes", dir="recv")
        self._c_corrupt = r.counter("serving.transport.corrupt_frames")

    @property
    def closed(self) -> bool:
        return self._closed

    def send(self, obj: Dict[str, Any]):
        _faults.maybe_fire("transport.send")
        if self._closed:
            raise TransportClosed("channel is closed")
        frame = encode_frame(obj)
        try:
            self._conn.send_bytes(frame)
        except (OSError, EOFError, BrokenPipeError) as e:
            self._closed = True
            raise TransportClosed(f"peer gone on send: {e}") from e
        self._c_sent.inc()
        self._b_sent.inc(len(frame))

    def recv(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        _faults.maybe_fire("transport.recv")
        if self._closed:
            raise TransportClosed("channel is closed")
        if timeout_s is not None:
            try:
                ready = self._conn.poll(timeout_s)
            except (OSError, EOFError, BrokenPipeError) as e:
                self._closed = True
                raise TransportClosed(f"peer gone on poll: {e}") from e
            if not ready:
                raise TransportTimeout(
                    f"recv timed out after {timeout_s:.3f}s")
        try:
            data = self._conn.recv_bytes()
        except EOFError as e:
            self._closed = True
            raise TransportClosed("peer closed the connection (EOF)") from e
        except (OSError, BrokenPipeError) as e:
            self._closed = True
            raise TransportClosed(f"peer gone on recv: {e}") from e
        self._c_recv.inc()
        self._b_recv.inc(len(data))
        try:
            return decode_frame(data)
        except TransportCorruption:
            self._c_corrupt.inc()
            raise

    def poll(self, timeout_s: float = 0.0) -> bool:
        if self._closed:
            return False
        try:
            return self._conn.poll(timeout_s)
        except (OSError, EOFError, BrokenPipeError):
            self._closed = True
            return False

    def close(self):
        if not self._closed:
            self._closed = True
            try:
                self._conn.close()
            except OSError:
                pass


# ---- payload codecs ---------------------------------------------------------
#
# Requests / results cross the boundary as plain JSON dicts.  Request
# ids are minted PARENT-side (`Request.__init__` numbers them through
# the process-global `_next_req_id`), so decode passes `request_id=`
# through — the worker's `_note_req_id` keeps its own mint ahead of
# everything it has seen, and uniqueness across workers is the parent's
# problem (it is the only minter).

def encode_request(req, tokens=None) -> Dict[str, Any]:
    # tpu-lint: allow(host-sync): API boundary — prompts are host ids
    d = {
        "rid": int(req.request_id),
        "prompt": np.asarray(req.prompt).astype(int).tolist(),
        "max_new_tokens": int(req.max_new_tokens),
        "seed": None if req.seed is None else int(req.seed),
        "deadline_s": req.deadline_s,
        "priority": req.priority,
        "trace_id": req.trace_id,
    }
    if tokens is not None:
        d["tokens"] = [int(t) for t in tokens]
    return d


def decode_request(d: Dict[str, Any]):
    from paddle_tpu.serving.engine import Request
    return Request(d["prompt"], max_new_tokens=d["max_new_tokens"],
                   seed=d.get("seed"), deadline_s=d.get("deadline_s"),
                   priority=d.get("priority", "normal"),
                   request_id=d["rid"], trace_id=d.get("trace_id"))


def encode_result(res) -> Dict[str, Any]:
    # tpu-lint: allow(host-sync): results are host token lists
    return {
        "rid": int(res.request_id),
        "prompt": np.asarray(res.prompt).astype(int).tolist(),
        "tokens": np.asarray(res.tokens).astype(int).tolist(),
        "gen_len": int(res.gen_len),
        "finish": res.finish,
        "ttft_s": res.ttft_s,
        "tpot_s": res.tpot_s,
        "prefix_hit_blocks": int(res.prefix_hit_blocks or 0),
        "trace_id": res.trace_id,
    }


def decode_result(d: Dict[str, Any]):
    from paddle_tpu.serving.engine import RequestResult
    # tpu-lint: allow(journal-coverage, host-sync): pure codec — the
    # finish HAPPENED worker-side (journaled by the router when it
    # collects the result); wire token lists are host ints
    return RequestResult(
        int(d["rid"]), np.asarray(d["prompt"], np.int32),
        np.asarray(d["tokens"], np.int32), int(d["gen_len"]), d["finish"],
        d.get("ttft_s"), d.get("tpot_s"), int(d.get("prefix_hit_blocks", 0)),
        trace_id=d.get("trace_id"))


# ---- prefix-block payloads (tier store, docs/SERVING.md §Hierarchical KV) --
#
# KV block payloads ride the same JSON frames as every other RPC:
# base64 bytes + dtype/shape, so the CRC framing, fault sites and
# greppability are inherited unchanged.  The codec round-trips bf16
# exactly (raw bytes, never a float cast) — a copied prefix block must
# be BITWISE the producing replica's block or the parity contract of
# the hierarchical KV tier breaks.

def encode_block_entries(entries: Dict[str, Tuple[int, Any]]
                         ) -> Dict[str, Dict[str, Any]]:
    """``{chain_key_hex: (depth, kv_array)}`` -> JSON-safe wire dict
    (the ``block_fetch`` reply / ``block_put`` request payload)."""
    out = {}
    for k, (depth, kv) in entries.items():
        # tpu-lint: allow(host-sync): wire payloads are host arrays
        kv = np.ascontiguousarray(kv)
        out[k] = {"d": int(depth), "dtype": str(kv.dtype),
                  "shape": [int(s) for s in kv.shape],
                  "b": base64.b64encode(kv.tobytes()).decode("ascii")}
    return out


def decode_block_entries(d: Dict[str, Dict[str, Any]]
                         ) -> Dict[str, Tuple[int, np.ndarray]]:
    """Inverse of :func:`encode_block_entries`. ``bfloat16`` resolves
    through ``ml_dtypes`` (jax's numpy dtype extensions) — imported
    lazily so the transport stays importable without an accelerator
    stack."""
    entries = {}
    for k, v in d.items():
        try:
            dt = np.dtype(v["dtype"])
        except TypeError:
            import ml_dtypes  # noqa: F401 — registers bf16 et al.
            dt = np.dtype(v["dtype"])
        kv = np.frombuffer(base64.b64decode(v["b"]),
                           dtype=dt).reshape(v["shape"])
        entries[k] = (int(v["d"]), kv)
    return entries


# ---- remote error envelope --------------------------------------------------
#
# Worker-side exceptions round-trip by TYPE NAME: the handful the router
# dispatches on (admission control, restore) reconstruct as their real
# classes; everything else degrades to `RemoteError` with the original
# type in the message — a worker bug must surface as a crash report,
# never as a silently-wrong RPC result.

def _error_types() -> Dict[str, type]:
    from paddle_tpu.analysis.runtime import SnapshotDriftError
    from paddle_tpu.serving.engine import Rejected, RestoreError
    from paddle_tpu.serving.pool import PoolExhausted
    return {
        "Rejected": Rejected, "RestoreError": RestoreError,
        "PoolExhausted": PoolExhausted, "ValueError": ValueError,
        "KeyError": KeyError, "FileNotFoundError": FileNotFoundError,
        "OSError": OSError, "RuntimeError": RuntimeError,
        "TimeoutError": TimeoutError,
        # the mid-soak sanitizer's verdict must keep its type across
        # the process boundary (chaos_bench exits 3 on it by class)
        "SnapshotDriftError": SnapshotDriftError,
    }


def encode_error(exc: BaseException) -> Dict[str, str]:
    d = {"type": type(exc).__name__, "msg": str(exc)}
    reason = getattr(exc, "reason", None)
    if isinstance(reason, str):    # Rejected carries a machine code
        d["reason"] = reason
    return d


def raise_remote(err: Dict[str, str]):
    cls = _error_types().get(err.get("type", ""))
    if cls is None:
        raise RemoteError(
            f"{err.get('type', 'Exception')}: {err.get('msg', '')}")
    if err.get("type") in ("Rejected", "RestoreError"):
        # two-arg ctor: (machine-readable reason, human message)
        raise cls(err.get("reason", "remote"), err.get("msg", ""))
    raise cls(err.get("msg", ""))
