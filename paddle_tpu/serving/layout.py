"""Tensor-parallel layout for one paged-serving replica (docs/SERVING.md
§Tensor-parallel replicas).

The reference serves a big model by sharding each fused_multi_transformer
layer over the ``mp`` process group (qkv/gate/up column-parallel, the KV
cache split by head) with one collective per layer at the o-proj
boundary. TPU-native, the process group is a mesh axis: this module maps
the engine's stacked per-layer weights, the paged KV pool, and the int8
scale twins to :class:`~jax.sharding.PartitionSpec` s over a
``{mp, fsdp}`` submesh (``parallel.topology`` axis names), and the
engine wraps its program sites in full-manual ``jax.shard_map`` with
these specs — SNIPPETS exemplar [3]'s ``SpecLayout``, specialized to the
serving engine's actual pytrees.

Parity-first sharding (the all_gather flavor, not psum): qkv / gate / up
projections are COLUMN-parallel — each shard computes its own heads'
attention and its own FFN lanes over exact full-width inputs — and the
(b, cols) activations are reassembled by one tiled ``all_gather`` before
the o-proj / down-proj, which stay replicated full-width matmuls. A
row-parallel o-proj with a ``psum`` would change the reduction order of
every output element and break the engine's bitwise token-parity pins;
gather-then-full-matmul reproduces the mp=1 float ops exactly (each
output element is one dot over the same operands in the same order), so
the mp=2 engine is bit-identical to mp=1 — the property
tests/test_serving_mp.py pins on a forced-host-device CPU mesh.

Shard-major permutations: the fused qkv stack packs columns ``[q|k|v]``
and the paged pool packs its last dim ``[k|v]`` — a contiguous mp-split
of either crosses region boundaries. The layout permutes those dims
shard-major (``ops.fused_decode.mp_qkv_permutation`` /
``mp_kv_permutation``) at device-placement time, so each shard's
contiguous block IS its canonical local ``[q_s|k_s|v_s]`` /
``[k_s|v_s]`` layout and the per-shard kernel code is unchanged. Host
mirrors (``_kv_scales``, snapshots, the prefix cache's bf16 copies)
stay canonical — only device twins are permuted.

``fsdp`` is the weight-memory axis: every stacked leaf is additionally
sharded on its layer dim (L) and gathered at use inside the shard body
(one tiled ``all_gather`` per decode program — classic
gather-at-use FSDP; bitwise inert, it reassembles the exact bytes).
Sampling, RNG streams, block tables and every host mirror stay
replicated, which is what keeps the engine's per-slot
``fold_in(seed, count)`` streams — and with them every token-parity
pin — intact verbatim.
"""

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ServingLayout"]

# stacked llama/gpt leaves whose LAST dim is the mp-sharded (column)
# dim: the fused qkv projection (+ its bias/scale rows, permuted
# shard-major) and the gate/up FFN lanes. Everything else — o/down
# projections, layernorms, their biases/scales — stays full-width on
# every shard (the gather-then-full-matmul parity contract above).
_MP_COL_KEYS = frozenset({
    "wqkv", "wqkv_s", "bqkv",       # fused [q|k|v], shard-major permuted
    "wg", "wg_s", "bg",             # gate/up FFN lanes, contiguous split
    "wu", "wu_s",
})
# leaves whose last dim is permuted shard-major with the qkv
# permutation before the contiguous mp split
_QKV_PERM_KEYS = frozenset({"wqkv", "wqkv_s", "bqkv"})


class ServingLayout:
    """PartitionSpecs for one tensor-parallel ServingEngine replica.

    ``mesh`` must carry the axes named by ``mp_axis`` / ``fsdp_axis``
    (either may be absent — its degree is then 1 and the corresponding
    sharding degrades to replication). Axis names default to the
    ``parallel.topology.KNOWN_AXES`` registry names, which is what
    keeps the mesh-lint ``collective-axis`` / ``pspec-axis`` rules able
    to pin them statically.
    """

    def __init__(self, mesh, *, mp_axis: str = "mp",
                 fsdp_axis: str = "fsdp"):
        if mesh is None:
            raise ValueError("ServingLayout needs a jax.sharding.Mesh")
        if mp_axis not in mesh.axis_names \
                and fsdp_axis not in mesh.axis_names:
            raise ValueError(
                f"mesh axes {mesh.axis_names} carry neither "
                f"{mp_axis!r} nor {fsdp_axis!r}; a serving layout "
                f"shards over those two axes only")
        self.mesh = mesh
        self.mp_axis = mp_axis if mp_axis in mesh.axis_names else None
        self.fsdp_axis = (fsdp_axis if fsdp_axis in mesh.axis_names
                          else None)
        self.mp = (int(mesh.shape[self.mp_axis])
                   if self.mp_axis is not None else 1)
        self.fsdp = (int(mesh.shape[self.fsdp_axis])
                     if self.fsdp_axis is not None else 1)
        for ax in mesh.axis_names:
            if ax not in (mp_axis, fsdp_axis) and mesh.shape[ax] != 1:
                raise ValueError(
                    f"mesh axis {ax!r} has degree {mesh.shape[ax]}; a "
                    f"single serving replica only shards over "
                    f"{mp_axis!r}/{fsdp_axis!r} — put data parallelism "
                    f"in Router replicas, not this mesh")
        # collapse degree-1 axes to None so the specs (and the program
        # cache keys derived from them) are canonical
        if self.mp == 1:
            self.mp_axis = None
        if self.fsdp == 1:
            self.fsdp_axis = None

    # ---------------------------------------------------------- validation
    def validate(self, *, num_heads: int, num_kv_heads: int,
                 num_layers: int, ffn: Optional[int] = None):
        """Divisibility gates, checked at engine construction (a trace
        error on a v5p mesh is the failure mode this pre-empts):
        mp must divide the head counts (each shard owns whole kv
        groups), fsdp must divide the layer count, and the (padded)
        ffn width must split evenly over mp."""
        if num_kv_heads % self.mp or num_heads % self.mp:
            raise ValueError(
                f"mp={self.mp} must divide num_heads={num_heads} and "
                f"num_kv_heads={num_kv_heads} (each shard walks whole "
                f"kv groups so its block-table gather stays local)")
        if num_layers % self.fsdp:
            raise ValueError(
                f"fsdp={self.fsdp} must divide num_layers="
                f"{num_layers} (stacked weights shard on the layer dim)")
        if ffn is not None and ffn % self.mp:
            raise ValueError(
                f"mp={self.mp} must divide the padded ffn width {ffn}")

    # ------------------------------------------------------------- specs
    @property
    def replicated(self) -> P:
        return P()

    def pool_spec(self) -> P:
        """The paged KV pool (L, num_blocks, block_tokens, 2*nkv*hd):
        sharded on the head (last) dim after the shard-major kv
        permutation — each shard's block-table walk reads only its own
        heads' lanes, no cross-shard traffic in the attention walk."""
        return P(None, None, None, self.mp_axis)

    def kv_scales_spec(self) -> P:
        """The int8 per-slot scale device twin (L, max_slots, 2*nkv*hd),
        permuted+sharded in lockstep with the pool's last dim."""
        return P(None, None, self.mp_axis)

    def stacked_specs(self, stacked: Dict) -> Dict[str, P]:
        """Per-leaf specs for a ``build_fused_params``-shaped stack
        (llama or gpt keys): column-parallel leaves shard their last
        dim over mp, every leaf shards its layer dim over fsdp."""
        out = {}
        for k, w in stacked.items():
            ax = [None] * w.ndim
            ax[0] = self.fsdp_axis
            if k in _MP_COL_KEYS:
                ax[-1] = self.mp_axis
            out[k] = P(*ax)
        return out

    # ------------------------------------------------------- permutations
    def qkv_perm(self, num_heads: int, num_kv_heads: int,
                 head_dim: int) -> np.ndarray:
        from paddle_tpu.ops.fused_decode import mp_qkv_permutation
        return mp_qkv_permutation(num_heads, num_kv_heads, head_dim,
                                  self.mp)

    def kv_perm(self, num_kv_heads: int, head_dim: int) -> np.ndarray:
        from paddle_tpu.ops.fused_decode import mp_kv_permutation
        return mp_kv_permutation(num_kv_heads, head_dim, self.mp)

    # --------------------------------------------------------- placement
    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def place(self, x, spec: P):
        """Commit ``x`` (host or device) to the mesh under ``spec``."""
        return jax.device_put(x, self.sharding(spec))

    def place_replicated(self, tree):
        """Commit a whole pytree replicated onto the mesh (device
        mirrors, draft weights/pool, program constants — anything a
        mesh-committed program consumes that is not sharded)."""
        return jax.device_put(tree, self.sharding(P()))

    def shard_stacked(self, stacked: Dict, *, num_heads: int,
                      num_kv_heads: int, head_dim: int) -> Dict:
        """Permute the fused-qkv leaves shard-major and commit every
        stacked leaf to the mesh under :meth:`stacked_specs`. The
        permutation is applied to the DEVICE twin only — host-side
        canonical forms (snapshots, state dicts) never see it."""
        perm = self.qkv_perm(num_heads, num_kv_heads, head_dim)
        specs = self.stacked_specs(stacked)
        out = {}
        for k, w in stacked.items():
            if k in _QKV_PERM_KEYS and self.mp > 1:
                # tpu-lint: allow(host-sync): one-time init permutation
                # of the device twin (host round trip, not a step cost)
                w = np.asarray(w)[..., perm]
            out[k] = self.place(w, specs[k])
        return out

    def shard_kv_scales(self, scales: np.ndarray, *, num_kv_heads: int,
                        head_dim: int):
        """Permute the canonical host scales (L, ms, [k|v]) shard-major
        and commit the device twin under :meth:`kv_scales_spec`."""
        # tpu-lint: allow(host-sync): scales are the host-canonical mirror
        s = np.asarray(scales)
        if self.mp > 1:
            s = s[..., self.kv_perm(num_kv_heads, head_dim)]
        return self.place(s, self.kv_scales_spec())

    def __repr__(self):
        return (f"ServingLayout(mp={self.mp}, fsdp={self.fsdp}, "
                f"mesh={dict(self.mesh.shape)})")
