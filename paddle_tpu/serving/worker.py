"""Cross-process replica tier: one OS process per replica
(docs/SERVING.md §Cross-process tier).

`worker_main` is the spawn entry of a replica child process: it builds
(or snapshot-restores) its own ``ServingEngine`` from a picklable
``model_factory`` and serves the engine's surface over a
`serving.transport.Channel` — one RPC in flight, reply piggybacking a
small status dict, which the single-client discipline (only the Router
talks to a worker) makes an EXACT cache, not an approximation.

`ReplicaProxy` is the router-side half: it duck-types the engine
surface the `Router` actually touches (submit / admit_resumable /
step / drain / save_snapshot / inflight_tokens / estimated_ttft_s /
stats / overload knobs / pool + prefix-cache occupancy views), so
placement, failover, journaling and the trace_id chains above the seam
are byte-for-byte the in-process code paths.  Robustness is layered:

* every call carries a wall-clock deadline (`TransportTimeout`
  distinguishes a HUNG worker from a dead one);
* idempotent calls retry under the shared `RetryPolicy`, seeded
  per-replica so N proxies retrying a dead peer de-correlate;
* `TransportClosed` (EOF — the process is gone) and retry exhaustion
  mark the proxy broken, SIGKILL-reap the child so it can never leak,
  and surface through the engine surface the router already handles:
  ``closed`` for the heartbeat, a raised error for the step path,
  ``Rejected("replica_unreachable")`` for placement — the
  healthy→suspect→dead machine and zero-loss failover take it from
  there, unchanged.

The spawn context (never fork — jax's thread pools do not survive
forking) matches `parallel/launch.py`; the worker process re-imports
paddle_tpu and jax from scratch, which is exactly the isolation being
bought: a replica segfault, OOM-kill or SIGKILL takes ONE engine, not
the router's heap and journal writer.
"""

import logging
import multiprocessing as mp
import os
import signal
import time
from dataclasses import replace as _dc_replace
from typing import Any, Dict, List, Optional

from paddle_tpu.serving.engine import Rejected
from paddle_tpu.serving.transport import (
    Channel, PROTOCOL_VERSION, TransportClosed, TransportCorruption,
    TransportError, TransportTimeout, decode_block_entries,
    decode_request, decode_result, encode_block_entries, encode_error,
    encode_request, encode_result, raise_remote)

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["ReplicaProxy", "worker_main"]

#: ops safe to re-send after a torn frame or timeout: pure queries plus
#: writes that converge (re-arming the same faults, re-saving the same
#: step's snapshot).  submit/step/drain are NOT here — a lost reply
#: leaves the worker's state unknown, so those mark the proxy broken
#: and let the router's failover machinery decide.  block_fetch /
#: block_put (tier prefix store) are NOT here either: a fetch gathers
#: live device blocks and a put adopts pool references — a replayed
#: half-delivered transfer would double-commit pool state, so the
#: router's best-effort share just drops the copy instead.
_IDEMPOTENT_OPS = frozenset({
    "ping", "status", "stats", "inflight", "estimated_ttft",
    "faults_fired", "save_snapshot", "snapshot_roundtrip",
    "set_overload", "clear_prefix", "reset_stats", "arm_faults",
    "disarm_faults",
})


# ---------------------------------------------------------- worker side
def _engine_status(eng) -> Dict[str, Any]:
    """The piggybacked status every reply carries — the proxy's exact
    cache of the worker's scheduler occupancy."""
    pc = eng.prefix_cache
    return {
        "active": eng.active_slots, "queued": eng.queued,
        "idle": eng.idle, "closed": eng.closed,
        "pool_used": eng.pool.used_blocks,
        "prefix_hits": 0 if pc is None else pc.hit_blocks,
        "prefix_lookups": 0 if pc is None else pc.lookup_blocks,
    }


def _build_engine(spec: Dict[str, Any]):
    """Build (or restore) the worker's engine. Returns
    ``(engine, restored, covered_rids)``."""
    from paddle_tpu.serving.engine import ServingEngine

    model = spec["model_factory"]()
    kwargs = dict(spec.get("engine_kwargs") or {})
    labels = {"replica": str(spec.get("replica", 0))}
    restore_root = spec.get("restore_root")
    if restore_root is not None:
        try:
            snap = ServingEngine.load_snapshot(restore_root)
            overrides = {"metrics_labels": labels}
            if kwargs.get("flight_dump_path") is not None:
                overrides["flight_dump_path"] = kwargs["flight_dump_path"]
            eng = ServingEngine.restore(model, snap, **overrides)
            covered = sorted({int(rs["request_id"]) for rs in
                              snap["slots"] + snap["queue"]})
            return eng, True, covered
        except FileNotFoundError:
            # never snapshotted (or wiped to force the redistribute
            # path) — a fresh build IS the contract, not a failure
            pass
        except Exception:   # noqa: BLE001 — fall back to a fresh build
            logger.warning("replica worker %s: snapshot restore failed; "
                           "building fresh", spec.get("replica"),
                           exc_info=True)
    eng = ServingEngine(model, seed=spec.get("seed", 0),
                        metrics_labels=labels, **kwargs)
    return eng, False, []


def _arm_worker_faults(specs: List[Dict[str, Any]]) -> int:
    """Rebuild a fault plan from JSON specs and arm it in THIS process
    — chaos drives engine-level sites (decode.dispatch,
    serving.snapshot, worker.tick) inside the worker that owns them."""
    from paddle_tpu.resilience import faults as _faults

    plan = _faults.FaultPlan()
    for s in specs:
        exc = None
        if s.get("kind", "raise") == "raise" and s.get("message"):
            exc = RuntimeError(s["message"])
        payload = {k: v for k, v in s.items()
                   if k not in ("site", "kind", "at", "count", "message")}
        plan.add(_faults.Fault(s["site"], kind=s.get("kind", "raise"),
                               at=s.get("at", 0), count=s.get("count", 1),
                               exc=exc, **payload))
    _faults.arm(plan)
    return len(plan.faults)


def _dispatch(eng, op: str, args: Dict[str, Any]):
    """Execute one RPC op against the worker's engine."""
    if op == "ping" or op == "status":
        return True
    if op == "submit":
        return int(eng.submit(decode_request(args["request"])))
    if op == "admit_resumable":
        return int(eng.admit_resumable(decode_request(args["request"]),
                                       tokens=args.get("tokens")))
    if op == "release_request":
        toks = eng.release_request(int(args["rid"]))
        return None if toks is None else [int(t) for t in toks]
    if op == "step":
        out = eng.step()
        results = [encode_result(eng.results.pop(rid))
                   for rid in out.get("finished", ())
                   if rid in eng.results]
        return {"active": out.get("active", 0),
                "queued": out.get("queued", 0),
                "finished": [int(r) for r in out.get("finished", ())],
                "results": results}
    if op == "drain":
        eng.drain(max_steps=args.get("max_steps"))
        results = [encode_result(r) for r in eng.results.values()]
        eng.results.clear()
        return {"results": results}
    if op == "inflight":
        return {str(rid): [int(t) for t in toks]
                for rid, toks in eng.inflight_tokens().items()}
    if op == "estimated_ttft":
        return eng.estimated_ttft_s(decode_request(args["request"]),
                                    default=args.get("default", 0.0))
    if op == "save_snapshot":
        return eng.save_snapshot(args["root"])
    if op == "snapshot_roundtrip":
        from paddle_tpu.analysis import runtime as _sanitizer
        _sanitizer.snapshot_roundtrip(eng)
        return True
    if op == "stats":
        return {k: v for k, v in eng.stats.items()
                if isinstance(v, (int, float))}
    if op == "reset_stats":
        eng.reset_stats()
        return True
    if op == "set_overload":
        if "max_queue" in args:
            eng.max_queue = args["max_queue"]
        if "shed_infeasible" in args:
            eng.shed_infeasible = bool(args["shed_infeasible"])
        return True
    if op == "clear_prefix":
        if eng.prefix_cache is not None:
            eng.prefix_cache.clear()
        return True
    if op == "block_fetch":
        return encode_block_entries(
            eng.export_prefix_blocks(args.get("keys") or []))
    if op == "block_put":
        return int(eng.import_prefix_blocks(
            decode_block_entries(args.get("entries") or {})))
    if op == "arm_faults":
        return _arm_worker_faults(args.get("faults") or [])
    if op == "disarm_faults":
        from paddle_tpu.resilience import faults as _faults
        _faults.disarm()
        return True
    if op == "faults_fired":
        from paddle_tpu.resilience import faults as _faults
        plan = _faults.armed()
        return 0 if plan is None else sum(f.fired for f in plan.faults)
    if op == "shutdown":
        return True
    raise ValueError(f"unknown worker op {op!r}")


def worker_main(conn, spec: Dict[str, Any]):
    """Child-process entry: build the engine, handshake, serve RPCs
    until shutdown or parent EOF. Runs under mp's spawn context — a
    fresh interpreter, fresh jax, fresh metrics registry."""
    from paddle_tpu.resilience import faults as _faults

    # the parent's ctrl-C must not tear workers mid-protocol; the
    # router shuts us down explicitly (or dies, which EOFs the pipe)
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    chan = Channel(conn)
    try:
        eng, restored, covered = _build_engine(spec)
    except BaseException as e:  # noqa: BLE001 — report, then die
        try:
            chan.send({"ok": False, "error": encode_error(e)})
        except TransportError:
            pass
        return
    chan.send({
        "ok": True, "pid": os.getpid(), "protocol": PROTOCOL_VERSION,
        "restored": restored, "covered": covered,
        "block_tokens": eng.block_tokens, "max_seq_len": eng.max_seq_len,
        "max_queue": eng.max_queue,
        "pool_blocks": eng.pool.num_blocks,
        "has_prefix_cache": eng.prefix_cache is not None,
        "status": _engine_status(eng),
    })
    while True:
        try:
            msg = chan.recv()
        except TransportClosed:
            break               # parent gone: nothing left to serve
        except TransportCorruption:
            continue            # torn inbound frame: drop, stay alive
        seq, op = msg.get("seq"), msg.get("op", "")
        try:
            # the per-message fault site: a 'hang' here holds the reply
            # open (a live-but-hung worker, for the wall-clock
            # heartbeat to catch); raising kinds surface as RPC errors
            f = _faults.maybe_fire("worker.tick")
            if f is not None and f.kind == "hang":
                time.sleep(float(f.payload.get("seconds", 3600.0)))
            out = _dispatch(eng, op, msg.get("args") or {})
            reply = {"seq": seq, "ok": True, "out": out,
                     "status": _engine_status(eng)}
        except Exception as e:  # noqa: BLE001 — every app error rides back
            reply = {"seq": seq, "ok": False, "error": encode_error(e),
                     "status": _engine_status(eng)}
        try:
            chan.send(reply)
        except TransportClosed:
            break
        if op == "shutdown":
            break
    try:
        eng.close()
    except Exception:   # noqa: BLE001 — exiting anyway
        pass


# ----------------------------------------------------------- proxy side
class _PoolView:
    """Router-visible occupancy of the worker's block pool:
    ``num_blocks`` is static (handshake), ``used_blocks`` reads the
    piggybacked status — exact under the single-client discipline."""

    __slots__ = ("_proxy", "num_blocks")

    def __init__(self, proxy, num_blocks: int):
        self._proxy = proxy
        self.num_blocks = int(num_blocks)

    @property
    def used_blocks(self) -> int:
        return int(self._proxy._status.get("pool_used", 0))


class _PrefixCacheView:
    """Hit/lookup counters of the worker's prefix cache (status
    piggyback) + the clear() control surface the benches use."""

    __slots__ = ("_proxy",)

    def __init__(self, proxy):
        self._proxy = proxy

    @property
    def hit_blocks(self) -> int:
        return int(self._proxy._status.get("prefix_hits", 0))

    @property
    def lookup_blocks(self) -> int:
        return int(self._proxy._status.get("prefix_lookups", 0))

    def clear(self):
        self._proxy._rpc("clear_prefix")


class ReplicaProxy:
    """The router-side handle of one worker process, duck-typing the
    engine surface the Router drives (class docstring up top has the
    failure semantics). Not thread-safe — one client, one call in
    flight, exactly like the in-process engine it stands in for."""

    def __init__(self, proc, chan, hello: Dict[str, Any], *, replica: int,
                 rpc_timeout_s: float, retry_policy):
        self._proc = proc
        self._chan = chan
        self.replica = int(replica)
        self.pid = int(hello["pid"])
        self.restored = bool(hello.get("restored"))
        self.covered = [int(r) for r in hello.get("covered", [])]
        self.block_tokens = int(hello["block_tokens"])
        self.max_seq_len = int(hello["max_seq_len"])
        self._max_queue = hello.get("max_queue")
        self._shed_infeasible = False
        self.pool = _PoolView(self, hello["pool_blocks"])
        self.prefix_cache = (_PrefixCacheView(self)
                             if hello.get("has_prefix_cache") else None)
        self.mesh = None        # processes mode is single-device per worker
        self.results: Dict[int, Any] = {}
        self._status: Dict[str, Any] = dict(hello.get("status") or {})
        self._stats_cache: Dict[str, float] = {}
        self._rpc_timeout_s = float(rpc_timeout_s)
        # per-replica seed: N proxies retrying the same dead peer must
        # not synchronize into a retry storm (seeded jitter, PR 4)
        self._retry = _dc_replace(retry_policy,
                                  seed=retry_policy.seed + self.replica)
        self._seq = 0
        self._closed = False
        self._kill_next_step = False
        from paddle_tpu.observability import registry
        self._reg = registry()

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def start(cls, model_factory, *, engine_kwargs: Dict[str, Any],
              replica: int, seed: int = 0,
              restore_root: Optional[str] = None,
              rpc_timeout_s: float = 180.0,
              start_timeout_s: float = 300.0,
              retry_policy=None) -> "ReplicaProxy":
        """Spawn one replica worker and handshake it. Raises
        ``RuntimeError`` when the worker fails to build its engine or
        does not answer inside ``start_timeout_s`` (the child is
        SIGKILL-reaped first — a failed start can never leak)."""
        from paddle_tpu.resilience.retry import RetryPolicy

        ctx = mp.get_context("spawn")
        parent_conn, child_conn = ctx.Pipe()
        spec = {"model_factory": model_factory,
                "engine_kwargs": dict(engine_kwargs),
                "replica": int(replica), "seed": int(seed),
                "restore_root": restore_root}
        proc = ctx.Process(target=worker_main, args=(child_conn, spec),
                           name=f"paddle-replica-{replica}", daemon=True)
        proc.start()
        child_conn.close()
        chan = Channel(parent_conn)
        try:
            hello = chan.recv(timeout_s=start_timeout_s)
        except TransportError as e:
            cls._reap_pid(proc)
            raise RuntimeError(
                f"replica {replica} worker failed to start: {e}") from e
        if not hello.get("ok"):
            cls._reap_pid(proc)
            err = hello.get("error") or {}
            raise RuntimeError(
                f"replica {replica} worker engine build failed: "
                f"{err.get('type')}: {err.get('msg')}")
        return cls(proc, chan, hello, replica=replica,
                   rpc_timeout_s=rpc_timeout_s,
                   retry_policy=retry_policy or RetryPolicy())

    @staticmethod
    def _reap_pid(proc):
        """Unconditional child reaping: SIGKILL + join — the one exit
        every failure path funnels through, so a wedged worker can
        never outlive its proxy."""
        try:
            if proc.is_alive():
                os.kill(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass
        proc.join(timeout=5.0)

    def _mark_broken(self, why: str):
        if self._closed:
            return
        self._closed = True
        logger.warning("replica %d worker marked broken (%s); reaping "
                       "pid %d", self.replica, why, self.pid)
        self._chan.close()
        self._reap_pid(self._proc)

    def close(self):
        """Graceful shutdown: best-effort shutdown RPC, then the same
        unconditional reap every path ends in. Idempotent."""
        if self._closed:
            return
        self._closed = True
        try:
            self._seq += 1
            self._chan.send({"seq": self._seq, "op": "shutdown",
                             "args": {}})
            self._chan.recv(timeout_s=5.0)
        except TransportError:
            pass
        self._chan.close()
        self._proc.join(timeout=5.0)
        self._reap_pid(self._proc)

    def kill(self, mid_step: bool = False):
        """Real process death (chaos): SIGKILL now, or — ``mid_step``
        — armed to land while the worker is computing its NEXT step
        RPC. Either way the proxy does NOT mark itself closed: the
        router must DISCOVER the death (EOF at the next heartbeat ping
        or step call), exactly like a production crash."""
        if mid_step and not self._status.get("idle", True):
            self._kill_next_step = True
            return
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass

    @property
    def closed(self) -> bool:
        return self._closed or self._chan.closed

    # ------------------------------------------------------------------ rpc
    def _rpc(self, op: str, args: Optional[Dict[str, Any]] = None, *,
             timeout_s: Optional[float] = None,
             after_send=None):
        """One framed call. Idempotent ops retry under the seeded
        policy; a lost reply on a non-idempotent op (or retry
        exhaustion, or EOF) marks the proxy broken and reaps the
        worker before re-raising — the router's health machinery sees
        a closed engine, never a half-alive one."""
        from paddle_tpu.resilience.retry import call_with_retry

        if self.closed:
            raise TransportClosed(
                f"replica {self.replica} worker is closed")
        deadline_total = (timeout_s if timeout_s is not None
                          else self._rpc_timeout_s)
        t_wall = time.time()
        t0 = time.perf_counter()

        def attempt():
            self._seq += 1
            seq = self._seq
            self._chan.send({"seq": seq, "op": op, "args": args or {}})
            if after_send is not None:
                after_send()
            deadline = time.perf_counter() + deadline_total
            while True:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TransportTimeout(
                        f"{op} to replica {self.replica} timed out "
                        f"after {deadline_total:.3f}s")
                reply = self._chan.recv(timeout_s=remaining)
                if reply.get("seq") == seq:
                    break
                # stale reply of an earlier timed-out call: drop it
            self._status = reply.get("status") or self._status
            if not reply.get("ok"):
                raise_remote(reply.get("error") or {})
            return reply.get("out")

        self._reg.counter("serving.transport.rpcs", op=op).inc()
        try:
            if op in _IDEMPOTENT_OPS:
                out = call_with_retry(
                    attempt, policy=self._retry,
                    retry_on=(TransportTimeout, TransportCorruption),
                    describe=f"transport.{op}")
            else:
                out = attempt()
        except TransportClosed as e:
            self._reg.counter("serving.transport.rpc_errors",
                              kind="closed").inc()
            self._mark_broken(f"{op}: {e}")
            raise
        except TransportTimeout as e:
            self._reg.counter("serving.transport.rpc_errors",
                              kind="timeout").inc()
            if op not in ("ping", "save_snapshot", "snapshot_roundtrip"):
                # a lost reply leaves non-idempotent state unknown; a
                # ping/snapshot timeout is a liveness datum the health
                # machine (not the transport) adjudicates
                self._mark_broken(f"{op}: {e}")
            raise
        except TransportCorruption as e:
            self._reg.counter("serving.transport.rpc_errors",
                              kind="corrupt").inc()
            self._mark_broken(f"{op}: {e}")
            raise
        dt = time.perf_counter() - t0
        self._reg.sketch("serving.transport.rpc_s").observe(dt)
        from paddle_tpu import observability as obs
        tr = obs.active_tracer()
        if tr is not None:
            tr.record("serving.transport.rpc", ts=t_wall, dur_s=dt,
                      op=op, replica=self.replica)
        return out

    # ----------------------------------------------------- engine surface
    def ping(self, timeout_s: Optional[float] = None) -> bool:
        """Wall-clock liveness probe: False on timeout (hung worker)
        or death — the router's heartbeat counts either as a miss."""
        if self.closed:
            return False
        try:
            self._rpc("ping", timeout_s=timeout_s)
            return True
        except TransportError:
            return False

    def submit(self, request) -> int:
        try:
            return int(self._rpc("submit",
                                 {"request": encode_request(request)}))
        except TransportError as e:
            raise Rejected("replica_unreachable",
                           f"replica {self.replica} worker gone during "
                           f"submit: {e}") from e

    def admit_resumable(self, request, tokens=None) -> int:
        args = {"request": encode_request(request)}
        if tokens is not None:
            args["tokens"] = [int(t) for t in tokens]
        try:
            return int(self._rpc("admit_resumable", args))
        except TransportError as e:
            raise Rejected("replica_unreachable",
                           f"replica {self.replica} worker gone during "
                           f"admit_resumable: {e}") from e

    def release_request(self, request_id: int) -> Optional[List[int]]:
        try:
            toks = self._rpc("release_request",
                             {"rid": int(request_id)})
        except TransportError:
            return None     # worker gone: failover re-places, not us
        return None if toks is None else [int(t) for t in toks]

    def step(self) -> Dict:
        after = None
        if self._kill_next_step:
            self._kill_next_step = False

            def after():
                # land the SIGKILL while the worker computes this tick:
                # the frame is on the wire, the worker is (after a
                # scheduling beat) inside engine.step()
                time.sleep(0.01)
                try:
                    os.kill(self.pid, signal.SIGKILL)
                except (ProcessLookupError, OSError):
                    pass
        out = self._rpc("step", after_send=after)
        for enc in out.get("results", ()):
            res = decode_result(enc)
            # tpu-lint: allow(journal-coverage): mirror of a finish that
            # happened worker-side — the ROUTER journals it when it
            # collects from self.results (the engine-tier finish site)
            self.results[res.request_id] = res
        return {"active": out.get("active", 0),
                "queued": out.get("queued", 0),
                "finished": [int(r) for r in out.get("finished", ())]}

    def drain(self, max_steps: Optional[int] = None) -> Dict[int, Any]:
        out = self._rpc("drain", {"max_steps": max_steps})
        for enc in out.get("results", ()):
            res = decode_result(enc)
            # tpu-lint: allow(journal-coverage): mirror of a worker-side
            # finish — journaled by the router at collection
            self.results[res.request_id] = res
        return self.results

    def inflight_tokens(self) -> Dict[int, List[int]]:
        try:
            out = self._rpc("inflight")
        except TransportError:
            # broken mid-query: report nothing held — the router's
            # orphan healer re-places from its own mirror (any prefix
            # is token-exact) and the reaped worker cannot double-run
            return {}
        return {int(rid): [int(t) for t in toks]
                for rid, toks in out.items()}

    def estimated_ttft_s(self, request, default: float = 0.0) -> float:
        try:
            out = self._rpc("estimated_ttft",
                            {"request": encode_request(request),
                             "default": default})
        except TransportError:
            return default
        return default if out is None else float(out)

    def save_snapshot(self, root: str,
                      timeout_s: Optional[float] = None) -> str:
        return self._rpc("save_snapshot", {"root": root},
                         timeout_s=timeout_s)

    def snapshot_roundtrip(self):
        """Run the PR 13 snapshot/restore sanitizer INSIDE the worker
        (the twin engine must live beside the real one); drift raises
        through the typed-error envelope."""
        return self._rpc("snapshot_roundtrip")

    def export_prefix_blocks(self, keys) -> Dict[str, Any]:
        """Fetch exact prefix-block payloads out of the worker's cache
        (tier store's ``block_fetch`` RPC — NOT idempotent, see
        ``_IDEMPOTENT_OPS``). Best-effort: a broken transport returns
        an empty dict and the router's share just shortens."""
        try:
            out = self._rpc("block_fetch", {"keys": list(keys)})
        except TransportError:
            return {}
        return decode_block_entries(out or {})

    def import_prefix_blocks(self, entries) -> int:
        """Deliver prefix-block payloads into the worker's cache (the
        ``block_put`` RPC — NOT idempotent). Best-effort: a broken
        transport imports nothing (returns 0)."""
        try:
            return int(self._rpc(
                "block_put",
                {"entries": encode_block_entries(entries)}))
        except TransportError:
            return 0

    def arm_faults(self, fault_specs: List[Dict[str, Any]]) -> int:
        """Arm a fault plan inside the worker process — chaos drives
        engine-level sites where the engine actually lives."""
        return int(self._rpc("arm_faults", {"faults": fault_specs}))

    def disarm_faults(self):
        return self._rpc("disarm_faults")

    def faults_fired(self) -> int:
        try:
            return int(self._rpc("faults_fired"))
        except TransportError:
            return 0

    @property
    def stats(self) -> Dict[str, float]:
        if not self.closed:
            try:
                self._stats_cache = dict(self._rpc("stats"))
            except Exception:   # noqa: BLE001 — telemetry, last cache wins
                pass
        return dict(self._stats_cache)

    def reset_stats(self):
        try:
            self._rpc("reset_stats")
        except TransportError:
            pass

    # overload knobs: setters mirror to the worker, getters serve the
    # router's template bookkeeping from the local mirror
    @property
    def max_queue(self):
        return self._max_queue

    @max_queue.setter
    def max_queue(self, v):
        self._max_queue = v
        try:
            self._rpc("set_overload", {"max_queue": v})
        except TransportError:
            pass

    @property
    def shed_infeasible(self):
        return self._shed_infeasible

    @shed_infeasible.setter
    def shed_infeasible(self, v):
        self._shed_infeasible = bool(v)
        try:
            self._rpc("set_overload", {"shed_infeasible": bool(v)})
        except TransportError:
            pass

    # status-cache views (exact: the worker only mutates on our RPCs)
    @property
    def active_slots(self) -> int:
        return int(self._status.get("active", 0))

    @property
    def queued(self) -> int:
        return int(self._status.get("queued", 0))

    @property
    def idle(self) -> bool:
        return bool(self._status.get("idle", True))
