"""Paged KV-cache pool: block allocator + content-hashed prefix cache.

The contiguous decode cache sizes every batch slot for prompt+max_new —
a request that finishes early strands its tail and a mixed-length batch
pads every slot to the longest member. The serving engine instead draws
fixed-size KV *blocks* from one shared pool (the vLLM paged-KV design,
PAPERS lineage) and maps each slot's logical cache through a per-slot
block table; this module is the host-side bookkeeping for that pool.

``BlockPool`` is a refcounted free-list allocator over physical block
ids. Block 0 is reserved as the *scratch* block: inactive batch rows and
the unallocated tail of every block table point at it, so the kernel's
data-dependent DMA descriptors always address a valid block (the reads
are masked, not skipped).

``PrefixCache`` content-hashes block-aligned prompt prefixes (a chain
hash, so a block's identity includes everything before it). Full prompt
blocks are shared copy-on-write across requests — trivially safe here
because decode only ever *appends*, and only the partially-filled tail
block of a prompt can receive appends; full blocks are immutable by
construction, so sharing them never needs an actual copy. A bf16 pool
shares the physical block (refcounted); an int8 pool cannot (blocks are
quantized with per-slot scales), so the cache keeps the exact bf16 KV
host-side and the engine re-quantizes it with the adopting request's own
scales — the prefill FLOPs are still skipped, which is the point.
"""

import hashlib
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["BlockPool", "PoolExhausted", "PrefixCache", "PrefixEntry",
           "SCRATCH_BLOCK"]

# physical block id 0: never allocated, target of every masked table entry
SCRATCH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """The pool has fewer free blocks than an allocation needs."""


class BlockPool:
    """Refcounted allocator over ``num_blocks`` physical KV blocks.

    Invariants (pinned by tests/test_serving.py):

    * block 0 (``SCRATCH_BLOCK``) is never handed out and never freed;
    * a block is on the free list iff its refcount is 0;
    * ``free()`` below refcount 0 raises — a double-free would let two
      slots write the same physical block.
    """

    def __init__(self, num_blocks: int, block_tokens: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 scratch + 1 usable), got {num_blocks}")
        if block_tokens % 8:
            raise ValueError(
                f"block_tokens must be a multiple of 8 (the kernel's RMW "
                f"row granularity), got {block_tokens}")
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        # LIFO free list: a just-freed block is re-issued first, so a hot
        # pool cycles a small working set of physical blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._refs = [0] * num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - 1 - len(self._free)

    def refcount(self, bid: int) -> int:
        return self._refs[bid]

    def alloc(self, n: int = 1) -> List[int]:
        """Take ``n`` blocks (refcount 1 each). Raises PoolExhausted —
        admission control is the caller's job; this is the backstop."""
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} KV blocks, pool has {len(self._free)} free "
                f"of {self.num_blocks - 1}")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def ref(self, bid: int) -> int:
        """Add a reference to an allocated block (prefix sharing)."""
        if bid == SCRATCH_BLOCK:
            raise ValueError("the scratch block cannot be shared")
        if self._refs[bid] <= 0:
            raise ValueError(f"block {bid} is not allocated")
        self._refs[bid] += 1
        return self._refs[bid]

    def free(self, bid: int) -> bool:
        """Drop one reference; returns True when the block went back to
        the free list (refcount hit 0)."""
        if bid == SCRATCH_BLOCK:
            raise ValueError("the scratch block cannot be freed")
        if self._refs[bid] <= 0:
            raise ValueError(f"double free of block {bid}")
        self._refs[bid] -= 1
        if self._refs[bid] == 0:
            self._free.append(bid)
            return True
        return False


def _chain_hash(parent: bytes, tokens: np.ndarray) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(parent)
    # tpu-lint: allow(host-sync): hashing host token ids (never device)
    h.update(np.ascontiguousarray(tokens, dtype=np.int64).tobytes())
    return h.digest()


class PrefixEntry:
    """One cached full prompt block.

    ``block_id`` — bf16 pools: the shared physical block (the cache holds
    its own pool reference). ``kv_host`` — int8 pools: the exact bf16 KV
    (L, block_tokens, 2*nkv*hd) kept host-side for re-quantization.
    """

    __slots__ = ("key", "depth", "block_id", "kv_host", "tick")

    def __init__(self, key: bytes, depth: int,
                 block_id: Optional[int] = None,
                 kv_host: Optional[np.ndarray] = None):
        self.key = key
        self.depth = depth          # chain position (0 = first block)
        self.block_id = block_id
        self.kv_host = kv_host
        self.tick = 0


class PrefixCache:
    """Chain-hashed prompt-prefix cache over a :class:`BlockPool`.

    ``lookup`` walks the longest cached chain of *full* blocks for a
    prompt; ``insert`` registers a freshly prefilled prompt's full
    blocks. Capacity is counted in blocks; eviction is LRU. Evicting a
    mid-chain entry merely shortens future lookups (lookup stops at the
    first missing link) — orphaned descendants age out the same way.
    """

    def __init__(self, pool: BlockPool, capacity_blocks: int = 256):
        self.pool = pool
        self.capacity = int(capacity_blocks)
        self._entries: Dict[bytes, PrefixEntry] = {}
        self._tick = 0
        self.hit_blocks = 0
        self.lookup_blocks = 0

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, prompt: Sequence[int],
               max_blocks: Optional[int] = None,
               record: bool = True) -> List[PrefixEntry]:
        """Longest cached chain of full blocks covering ``prompt``.

        ``max_blocks`` caps the walk — the engine passes
        ``(len(prompt) - 1) // block_tokens`` so at least one prompt
        token is always left to prefill (its logits seed sampling).
        ``record=False`` probes without touching the hit/lookup counters
        or LRU ticks — the engine's admission check may re-probe the
        same blocked head-of-line request every tick, which must not
        inflate the hit rate or keep its entries artificially hot; it
        calls :meth:`commit` once when the request is actually admitted.
        """
        bt = self.pool.block_tokens
        # tpu-lint: allow(host-sync): prompts arrive as host ids
        prompt = np.asarray(prompt)
        n_full = len(prompt) // bt
        if max_blocks is not None:
            n_full = min(n_full, max_blocks)
        out: List[PrefixEntry] = []
        parent = b""
        for c in range(n_full):
            key = _chain_hash(parent, prompt[c * bt:(c + 1) * bt])
            e = self._entries.get(key)
            if e is None:
                break
            out.append(e)
            parent = key
        if record:
            self.commit(out, n_full)
        return out

    def commit(self, hits: Sequence[PrefixEntry], n_lookup: int):
        """Account a ``record=False`` probe: bump hit/lookup counters
        and refresh the hit entries' LRU ticks."""
        self.lookup_blocks += n_lookup
        self.hit_blocks += len(hits)
        for e in hits:
            self._tick += 1
            e.tick = self._tick

    def insert(self, prompt: Sequence[int], n_reused: int,
               block_ids: Optional[Sequence[int]] = None,
               kv_host: Optional[Sequence[np.ndarray]] = None) -> int:
        """Register the full blocks of a just-prefilled prompt.

        ``n_reused`` leading blocks came from this cache (already
        present). For each NEW full block ``c`` provide either its
        physical ``block_ids[c - n_reused]`` (bf16 pool — the cache takes
        its own pool reference, so the block outlives the producing
        request) or ``kv_host[c - n_reused]`` (int8 pool). Returns the
        number of entries added.
        """
        bt = self.pool.block_tokens
        # tpu-lint: allow(host-sync): prompts arrive as host ids
        prompt = np.asarray(prompt)
        n_full = len(prompt) // bt
        parent = b""
        added = 0
        for c in range(n_full):
            key = _chain_hash(parent, prompt[c * bt:(c + 1) * bt])
            if c >= n_reused and key not in self._entries:
                i = c - n_reused
                bid = block_ids[i] if block_ids is not None else None
                kv = kv_host[i] if kv_host is not None else None
                if bid is None and kv is None:
                    break       # caller ran out of payload (capped insert)
                if bid is not None:
                    self.pool.ref(bid)
                e = PrefixEntry(key, c, block_id=bid, kv_host=kv)
                self._tick += 1
                e.tick = self._tick
                self._entries[key] = e
                added += 1
            parent = key
        self._evict()
        return added

    def _evict(self):
        while len(self._entries) > self.capacity:
            key = min(self._entries, key=lambda k: self._entries[k].tick)
            e = self._entries.pop(key)
            if e.block_id is not None:
                self.pool.free(e.block_id)

    def evictable_count(self, keep: Sequence = ()) -> int:
        """How many physical blocks :meth:`evict_free` could reclaim
        right now (cache-only references, not in ``keep``) — the upper
        bound admission's preemption feasibility pre-check adds to the
        free pool before deciding whether evicting/preempting can ever
        cover a shortfall."""
        skip = {id(e) for e in keep}
        return sum(1 for e in self._entries.values()
                   if e.block_id is not None and id(e) not in skip
                   and self.pool.refcount(e.block_id) == 1)

    def evict_free(self, n_blocks: int, keep: Sequence = ()) -> int:
        """Return up to ``n_blocks`` physical blocks to the pool by
        evicting LRU entries the cache ALONE still references (refcount
        1 — a block a live slot shares is pinned by that slot's ref and
        freeing the cache's ref would release nothing). The engine calls
        this when admission stalls on pool pressure: cached-but-idle
        prefix blocks are reclaimable capacity, not permanent residents.
        ``keep`` entries (this admission's own hits) are never evicted.
        Returns the number of blocks actually freed."""
        skip = {id(e) for e in keep}
        freed = 0
        for key in sorted(self._entries,
                          key=lambda k: self._entries[k].tick):
            if freed >= n_blocks:
                break
            e = self._entries[key]
            if id(e) in skip or e.block_id is None:
                continue
            if self.pool.refcount(e.block_id) == 1:
                self.pool.free(e.block_id)
                del self._entries[key]
                freed += 1
        return freed

    def keys(self) -> List[str]:
        """Hex digests of every cached chain key (engine snapshots carry
        them so a postmortem can see what was shared at crash time; the
        payloads — device blocks / host KV copies — do not survive a
        restore, which re-populates the cache organically)."""
        return [k.hex() for k in self._entries]

    def clear(self):
        for e in self._entries.values():
            if e.block_id is not None:
                self.pool.free(e.block_id)
        self._entries.clear()

    @property
    def hit_rate(self) -> float:
        return self.hit_blocks / self.lookup_blocks if self.lookup_blocks \
            else 0.0
