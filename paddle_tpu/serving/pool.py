"""Paged KV-cache pool: block allocator + content-hashed prefix cache.

The contiguous decode cache sizes every batch slot for prompt+max_new —
a request that finishes early strands its tail and a mixed-length batch
pads every slot to the longest member. The serving engine instead draws
fixed-size KV *blocks* from one shared pool (the vLLM paged-KV design,
PAPERS lineage) and maps each slot's logical cache through a per-slot
block table; this module is the host-side bookkeeping for that pool.

``BlockPool`` is a refcounted free-list allocator over physical block
ids. Block 0 is reserved as the *scratch* block: inactive batch rows and
the unallocated tail of every block table point at it, so the kernel's
data-dependent DMA descriptors always address a valid block (the reads
are masked, not skipped).

``PrefixCache`` content-hashes block-aligned prompt prefixes (a chain
hash, so a block's identity includes everything before it). Full prompt
blocks are shared copy-on-write across requests — trivially safe here
because decode only ever *appends*, and only the partially-filled tail
block of a prompt can receive appends; full blocks are immutable by
construction, so sharing them never needs an actual copy. A bf16 pool
shares the physical block (refcounted); an int8 pool cannot (blocks are
quantized with per-slot scales), so the cache keeps the exact bf16 KV
host-side and the engine re-quantizes it with the adopting request's own
scales — the prefill FLOPs are still skipped, which is the point.
"""

import hashlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

__all__ = ["BlockPool", "HostBlockStore", "PoolExhausted", "PrefixCache",
           "PrefixEntry", "SCRATCH_BLOCK", "TierPrefixStore",
           "chain_keys"]

# physical block id 0: never allocated, target of every masked table entry
SCRATCH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """The pool has fewer free blocks than an allocation needs."""


class BlockPool:
    """Refcounted allocator over ``num_blocks`` physical KV blocks.

    Invariants (pinned by tests/test_serving.py):

    * block 0 (``SCRATCH_BLOCK``) is never handed out and never freed;
    * a block is on the free list iff its refcount is 0;
    * ``free()`` below refcount 0 raises — a double-free would let two
      slots write the same physical block.
    """

    def __init__(self, num_blocks: int, block_tokens: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 scratch + 1 usable), got {num_blocks}")
        if block_tokens % 8:
            raise ValueError(
                f"block_tokens must be a multiple of 8 (the kernel's RMW "
                f"row granularity), got {block_tokens}")
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        # LIFO free list: a just-freed block is re-issued first, so a hot
        # pool cycles a small working set of physical blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._refs = [0] * num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - 1 - len(self._free)

    def refcount(self, bid: int) -> int:
        return self._refs[bid]

    def alloc(self, n: int = 1) -> List[int]:
        """Take ``n`` blocks (refcount 1 each). Raises PoolExhausted —
        admission control is the caller's job; this is the backstop."""
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} KV blocks, pool has {len(self._free)} free "
                f"of {self.num_blocks - 1}")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def ref(self, bid: int) -> int:
        """Add a reference to an allocated block (prefix sharing)."""
        if bid == SCRATCH_BLOCK:
            raise ValueError("the scratch block cannot be shared")
        if self._refs[bid] <= 0:
            raise ValueError(f"block {bid} is not allocated")
        self._refs[bid] += 1
        return self._refs[bid]

    def free(self, bid: int) -> bool:
        """Drop one reference; returns True when the block went back to
        the free list (refcount hit 0)."""
        if bid == SCRATCH_BLOCK:
            raise ValueError("the scratch block cannot be freed")
        if self._refs[bid] <= 0:
            raise ValueError(f"double free of block {bid}")
        self._refs[bid] -= 1
        if self._refs[bid] == 0:
            self._free.append(bid)
            return True
        return False


class HostBlockStore:
    """Host-RAM second tier of the paged KV pool (docs/SERVING.md
    §Hierarchical KV).

    Holds evicted/parked KV block payloads — numpy arrays of shape
    ``(L, block_tokens, 2*nkv*hd)`` in the pool's cache dtype — keyed by
    a store-minted integer id. The device :class:`BlockPool` stays the
    only authority over physical HBM blocks; this store is where a
    preempted slot's blocks LAND (swap-out) and where resume gathers
    them back FROM (swap-in), so parking costs host DRAM instead of
    either HBM residency or a full re-prefill.

    Capacity is counted in blocks, like the device pool. ``reserve``
    makes admission-style feasibility explicit: the engine reserves
    before it dispatches the device→host gather, so an overfull tier
    falls back to the legacy free+recompute path instead of partially
    swapping. int8 pools store the per-slot scale rows alongside the
    payload (quantized blocks are meaningless without them).
    """

    def __init__(self, capacity_blocks: int):
        if capacity_blocks < 1:
            raise ValueError(
                f"need >= 1 host block, got {capacity_blocks}")
        self.capacity = int(capacity_blocks)
        self._payloads: Dict[int, np.ndarray] = {}
        self._next_id = 1
        self._reserved = 0
        self.bytes_in = 0           # cumulative D2H traffic landed here
        self.bytes_out = 0          # cumulative H2D traffic served

    def __len__(self) -> int:
        return len(self._payloads)

    @property
    def used_blocks(self) -> int:
        return len(self._payloads) + self._reserved

    @property
    def free_blocks(self) -> int:
        return self.capacity - self.used_blocks

    @property
    def bytes_used(self) -> int:
        return sum(p.nbytes for p in self._payloads.values())

    def reserve(self, n: int) -> bool:
        """Claim capacity for ``n`` blocks ahead of an async swap-out;
        False (never raises) when the tier cannot take them — the
        caller keeps the legacy drop path."""
        if n > self.free_blocks:
            return False
        self._reserved += n
        return True

    def unreserve(self, n: int):
        self._reserved -= n
        assert self._reserved >= 0, "unreserve below zero"

    def put(self, payloads: Sequence[np.ndarray],
            reserved: bool = True) -> List[int]:
        """Land drained block payloads; returns their host ids. With
        ``reserved=True`` consumes a prior :meth:`reserve` claim."""
        if reserved:
            self.unreserve(len(payloads))
        elif len(payloads) > self.free_blocks:
            raise PoolExhausted(
                f"host tier needs {len(payloads)} blocks, has "
                f"{self.free_blocks} free of {self.capacity}")
        out = []
        for p in payloads:
            hid = self._next_id
            self._next_id += 1
            self._payloads[hid] = p
            self.bytes_in += p.nbytes
            out.append(hid)
        return out

    def get(self, host_ids: Sequence[int]) -> List[np.ndarray]:
        """Read payloads for swap-in (ids stay resident until freed —
        a failed swap-in must be retryable)."""
        out = [self._payloads[h] for h in host_ids]
        self.bytes_out += sum(p.nbytes for p in out)
        return out

    def free(self, host_ids: Sequence[int]):
        for h in host_ids:
            del self._payloads[h]

    def clear(self):
        self._payloads.clear()
        self._reserved = 0


def _chain_hash(parent: bytes, tokens: np.ndarray) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(parent)
    # tpu-lint: allow(host-sync): hashing host token ids (never device)
    h.update(np.ascontiguousarray(tokens, dtype=np.int64).tobytes())
    return h.digest()


def chain_keys(tokens: Sequence[int], block_tokens: int) -> List[str]:
    """Hex chain keys of every FULL block of ``tokens`` — the same hash
    walk :class:`PrefixCache` uses, exposed so the Router can name a
    prompt's blocks without owning a pool (tier-wide prefix store)."""
    # tpu-lint: allow(host-sync): prompts/token lists arrive as host ids
    tokens = np.asarray(tokens)
    out, parent = [], b""
    for c in range(len(tokens) // block_tokens):
        parent = _chain_hash(
            parent, tokens[c * block_tokens:(c + 1) * block_tokens])
        out.append(parent.hex())
    return out


class PrefixEntry:
    """One cached full prompt block.

    ``block_id`` — bf16 pools: the shared physical block (the cache holds
    its own pool reference). ``kv_host`` — int8 pools: the exact bf16 KV
    (L, block_tokens, 2*nkv*hd) kept host-side for re-quantization.
    """

    __slots__ = ("key", "depth", "block_id", "kv_host", "tick")

    def __init__(self, key: bytes, depth: int,
                 block_id: Optional[int] = None,
                 kv_host: Optional[np.ndarray] = None):
        self.key = key
        self.depth = depth          # chain position (0 = first block)
        self.block_id = block_id
        self.kv_host = kv_host
        self.tick = 0


class PrefixCache:
    """Chain-hashed prompt-prefix cache over a :class:`BlockPool`.

    ``lookup`` walks the longest cached chain of *full* blocks for a
    prompt; ``insert`` registers a freshly prefilled prompt's full
    blocks. Capacity is counted in blocks; eviction is LRU. Evicting a
    mid-chain entry merely shortens future lookups (lookup stops at the
    first missing link) — orphaned descendants age out the same way.
    """

    def __init__(self, pool: BlockPool, capacity_blocks: int = 256):
        self.pool = pool
        self.capacity = int(capacity_blocks)
        self._entries: Dict[bytes, PrefixEntry] = {}
        self._tick = 0
        self.hit_blocks = 0
        self.lookup_blocks = 0

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, prompt: Sequence[int],
               max_blocks: Optional[int] = None,
               record: bool = True) -> List[PrefixEntry]:
        """Longest cached chain of full blocks covering ``prompt``.

        ``max_blocks`` caps the walk — the engine passes
        ``(len(prompt) - 1) // block_tokens`` so at least one prompt
        token is always left to prefill (its logits seed sampling).
        ``record=False`` probes without touching the hit/lookup counters
        or LRU ticks — the engine's admission check may re-probe the
        same blocked head-of-line request every tick, which must not
        inflate the hit rate or keep its entries artificially hot; it
        calls :meth:`commit` once when the request is actually admitted.
        """
        bt = self.pool.block_tokens
        # tpu-lint: allow(host-sync): prompts arrive as host ids
        prompt = np.asarray(prompt)
        n_full = len(prompt) // bt
        if max_blocks is not None:
            n_full = min(n_full, max_blocks)
        out: List[PrefixEntry] = []
        parent = b""
        for c in range(n_full):
            key = _chain_hash(parent, prompt[c * bt:(c + 1) * bt])
            e = self._entries.get(key)
            if e is None:
                break
            out.append(e)
            parent = key
        if record:
            self.commit(out, n_full)
        return out

    def commit(self, hits: Sequence[PrefixEntry], n_lookup: int):
        """Account a ``record=False`` probe: bump hit/lookup counters
        and refresh the hit entries' LRU ticks."""
        self.lookup_blocks += n_lookup
        self.hit_blocks += len(hits)
        for e in hits:
            self._tick += 1
            e.tick = self._tick

    def insert(self, prompt: Sequence[int], n_reused: int,
               block_ids: Optional[Sequence[int]] = None,
               kv_host: Optional[Sequence[np.ndarray]] = None) -> int:
        """Register the full blocks of a just-prefilled prompt.

        ``n_reused`` leading blocks came from this cache (already
        present). For each NEW full block ``c`` provide either its
        physical ``block_ids[c - n_reused]`` (bf16 pool — the cache takes
        its own pool reference, so the block outlives the producing
        request) or ``kv_host[c - n_reused]`` (int8 pool). Returns the
        number of entries added.
        """
        bt = self.pool.block_tokens
        # tpu-lint: allow(host-sync): prompts arrive as host ids
        prompt = np.asarray(prompt)
        n_full = len(prompt) // bt
        parent = b""
        added = 0
        for c in range(n_full):
            key = _chain_hash(parent, prompt[c * bt:(c + 1) * bt])
            if c >= n_reused and key not in self._entries:
                i = c - n_reused
                bid = block_ids[i] if block_ids is not None else None
                kv = kv_host[i] if kv_host is not None else None
                if bid is None and kv is None:
                    break       # caller ran out of payload (capped insert)
                if bid is not None:
                    self.pool.ref(bid)
                e = PrefixEntry(key, c, block_id=bid, kv_host=kv)
                self._tick += 1
                e.tick = self._tick
                self._entries[key] = e
                added += 1
            parent = key
        self._evict()
        return added

    def _evict(self):
        while len(self._entries) > self.capacity:
            key = min(self._entries, key=lambda k: self._entries[k].tick)
            e = self._entries.pop(key)
            if e.block_id is not None:
                self.pool.free(e.block_id)

    def evictable_count(self, keep: Sequence = ()) -> int:
        """How many physical blocks :meth:`evict_free` could reclaim
        right now (cache-only references, not in ``keep``) — the upper
        bound admission's preemption feasibility pre-check adds to the
        free pool before deciding whether evicting/preempting can ever
        cover a shortfall."""
        skip = {id(e) for e in keep}
        return sum(1 for e in self._entries.values()
                   if e.block_id is not None and id(e) not in skip
                   and self.pool.refcount(e.block_id) == 1)

    def evict_free(self, n_blocks: int, keep: Sequence = ()) -> int:
        """Return up to ``n_blocks`` physical blocks to the pool by
        evicting LRU entries the cache ALONE still references (refcount
        1 — a block a live slot shares is pinned by that slot's ref and
        freeing the cache's ref would release nothing). The engine calls
        this when admission stalls on pool pressure: cached-but-idle
        prefix blocks are reclaimable capacity, not permanent residents.
        ``keep`` entries (this admission's own hits) are never evicted.
        Returns the number of blocks actually freed."""
        skip = {id(e) for e in keep}
        freed = 0
        for key in sorted(self._entries,
                          key=lambda k: self._entries[k].tick):
            if freed >= n_blocks:
                break
            e = self._entries[key]
            if id(e) in skip or e.block_id is None:
                continue
            if self.pool.refcount(e.block_id) == 1:
                self.pool.free(e.block_id)
                del self._entries[key]
                freed += 1
        return freed

    def entry(self, key_hex: str) -> Optional[PrefixEntry]:
        """The cached entry for one hex chain key (the tier-wide prefix
        store's fetch path) — refreshes its LRU tick: a block another
        replica asks for is a hot block."""
        e = self._entries.get(bytes.fromhex(key_hex))
        if e is not None:
            self._tick += 1
            e.tick = self._tick
        return e

    def adopt_entry(self, key_hex: str, depth: int,
                    block_id: Optional[int] = None,
                    kv_host: Optional[np.ndarray] = None) -> bool:
        """Register one externally-supplied chain entry (tier-wide
        prefix imports: the payload was prefilled on ANOTHER replica
        and block-copied here). Unlike :meth:`insert`, ownership of
        ``block_id``'s pool reference TRANSFERS to the cache — the
        engine allocates, scatters, then adopts. Returns False when
        the key is already cached (the caller frees its block)."""
        key = bytes.fromhex(key_hex)
        if key in self._entries:
            return False
        e = PrefixEntry(key, int(depth), block_id=block_id,
                        kv_host=kv_host)
        self._tick += 1
        e.tick = self._tick
        self._entries[key] = e
        self._evict()
        return True

    def keys(self) -> List[str]:
        """Hex digests of every cached chain key (engine snapshots carry
        them so a postmortem can see what was shared at crash time; the
        payloads — device blocks / host KV copies — do not survive a
        restore, which re-populates the cache organically)."""
        return [k.hex() for k in self._entries]

    def clear(self):
        for e in self._entries.values():
            if e.block_id is not None:
                self.pool.free(e.block_id)
        self._entries.clear()

    @property
    def hit_rate(self) -> float:
        return self.hit_blocks / self.lookup_blocks if self.lookup_blocks \
            else 0.0


class TierPrefixStore:
    """Tier-wide prefix index + host payload cache, owned by the Router
    (docs/SERVING.md §Hierarchical KV).

    Per-replica :class:`PrefixCache` instances only ever reuse work
    their OWN replica did; the router's affinity hash merely hopes that
    repeats land together. This store closes the gap: it maps chain
    keys (hex, :func:`chain_keys`) to the set of replicas believed to
    hold them, plus an LRU host cache of the exact bf16 payloads, so a
    prefix prefilled on replica A becomes a block copy — not a
    recompute — on replica B.

    The router is the only writer (single-threaded step loop), and the
    index is a HINT, not truth: a replica may have evicted an entry the
    index still names, in which case the fetch returns a subset and
    :meth:`forget` trims the hint. Losing the whole store costs only
    future copies — it is rebuilt organically from placements — so it
    is volatile state outside the journal/snapshot protocol.
    """

    def __init__(self, capacity_blocks: int = 256):
        self.capacity = int(capacity_blocks)
        self._owners: Dict[str, Set[int]] = {}
        self._payloads: Dict[str, Tuple[int, np.ndarray]] = {}
        self._ticks: Dict[str, int] = {}
        self._tick = 0
        self.hit_blocks = 0         # blocks served by cross-replica copy
        self.lookup_blocks = 0      # blocks probed at placement time

    def __len__(self) -> int:
        return len(self._payloads)

    @property
    def hit_rate(self) -> float:
        return self.hit_blocks / self.lookup_blocks if self.lookup_blocks \
            else 0.0

    def note_owner(self, keys: Sequence[str], replica: int):
        """Record that ``replica`` (just placed / just shared-to) will
        hold these chain keys."""
        for k in keys:
            self._owners.setdefault(k, set()).add(replica)

    def forget(self, keys: Sequence[str], replica: int):
        for k in keys:
            owners = self._owners.get(k)
            if owners is not None:
                owners.discard(replica)
                if not owners:
                    del self._owners[k]

    def forget_replica(self, replica: int):
        """Drop a dead/drained replica from every hint."""
        for k in list(self._owners):
            self.forget((k,), replica)

    def missing_run(self, keys: Sequence[str], replica: int
                    ) -> List[str]:
        """The leading run of chain keys ``replica`` lacks but some
        OTHER replica (or the host cache) can supply — chain order
        matters because a prefix lookup stops at the first missing
        link, so a non-contiguous copy would never be hit."""
        out: List[str] = []
        for k in keys:
            owners = self._owners.get(k, ())
            if replica in owners:
                if out:
                    break       # replica's own coverage resumes: stop
                continue        # replica already holds the chain so far
            if k not in self._payloads and not owners:
                break           # nobody can supply this link
            out.append(k)
        return out

    def owner_of(self, key: str, exclude: int) -> Optional[int]:
        for o in sorted(self._owners.get(key, ())):
            if o != exclude:
                return o
        return None

    def cached(self, key: str) -> Optional[Tuple[int, np.ndarray]]:
        hit = self._payloads.get(key)
        if hit is not None:
            self._tick += 1
            self._ticks[key] = self._tick
        return hit

    def put(self, key: str, depth: int, kv: np.ndarray):
        self._tick += 1
        self._payloads[key] = (int(depth), kv)
        self._ticks[key] = self._tick
        while len(self._payloads) > self.capacity:
            lru = min(self._ticks, key=self._ticks.get)
            del self._payloads[lru]
            del self._ticks[lru]

    def clear(self):
        self._owners.clear()
        self._payloads.clear()
        self._ticks.clear()
