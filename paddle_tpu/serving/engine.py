"""Continuous-batching serving engine over the fused paged-decode kernel.

``inference.generate`` runs one fixed batch to ``max_new_tokens`` in a
single dispatch: a request that finishes early burns full decode steps
emitting eos padding, and a request that arrives late waits for the
whole batch to drain. This engine (the Orca continuous-batching /
vLLM paged-KV design, PAPERS lineage) instead schedules at *slot*
granularity over a shared paged KV pool:

* **join** — a queued request is admitted when a batch slot and enough
  pool blocks are free; prefill runs apart from the decode dispatch
  (reusing any content-hashed cached prefix blocks, and admissions that
  land on the same tick share one batched prefill program per prompt
  shape), then the slot joins the running decode batch mid-flight;
* **leave** — a slot that hits eos, its token budget, or its deadline
  retires immediately: its blocks return to the pool the same step, no
  eos-padding decode steps are spent on it;
* every decode step is ONE dispatch of the fused paged kernel for all
  active slots, whatever their lengths — per-row positions mask the
  online-softmax walk, so mixed-length slots share the program.

Parity contract (tests/test_serving.py): with greedy sampling a
request's tokens from a merged continuously-batched run are identical to
an isolated ``generate`` call — per-request RNG streams
(``fold_in(PRNGKey(request_seed), t)``) make that hold for sampled
tokens too, because a row's stream never depends on its batch
neighbours.

Overload robustness (docs/SERVING.md §Overload behavior,
tests/test_serving_robustness.py):

* **bounded admission + typed shedding** — ``max_queue`` caps the
  queue; ``shed_infeasible`` rejects requests whose deadline cannot
  even reach a first token under the EWMA capacity estimate. Both shed
  paths raise :class:`Rejected` with a machine-readable ``reason``
  (counted under ``serving.rejected{reason}``) instead of queuing work
  that can only expire;
* **priority preemption with token-exact resume** — per-request
  ``priority`` classes order the queue; when a higher-priority request
  cannot be admitted, the scheduler retires the
  lowest-priority/loosest-deadline slot, frees its blocks and requeues
  it with its generated-so-far tokens. Resume re-prefills the PROMPT
  through the normal wave-prefill program (bitwise the original
  admission's program), REPLAYS the generated tokens through the real
  decode step program (recomputing them via the prefill forward
  rounds one bf16 ulp differently and can flip a near-tie argmax),
  and continues sampling at ``fold_in(seed, count)`` — the same RNG
  stream position an uninterrupted run would use. Together that keeps
  preempt/resume token-identical (greedy and sampled, bf16 and int8);
* **crash-recoverable state** — :meth:`ServingEngine.snapshot` /
  :meth:`save_snapshot` serialize the queue, per-slot generated tokens
  and finished results through the PR 4 integrity-manifest commit
  protocol; :meth:`ServingEngine.restore` re-admits every request via
  the resume path, so a mid-step fault loses nothing.

Chunked prefill (``chunk_tokens=``; docs/SERVING.md §Chunked prefill):
the wave prefill is one blocking program per prompt shape, so a single
long prompt stalls every active decode slot for its whole prefill — a
``serving.step_prefill_s`` outlier and a TPOT p99 spike under a
long-prompt mix. With ``chunk_tokens`` set, an admitted prompt is
processed ``chunk_tokens`` tokens at a time (Sarathi-style), and each
chunk tick is ONE fused program — true coscheduling: the front
group's next chunk AND every decode-ready slot's next token (or
speculative verify tail) dispatch together, with the chunk's block
scatter folded into the decode step's pool pass
(``ops.fused_decode.fused_paged_tick_step``). The per-chunk KV
staging round trip is gone: bf16 mid chunks gather their processed
prefix straight from pool blocks (no carry buffer at all), and int8
prefills thread ONE fixed-shape resident bf16 carry, donated and
RMW'd in place across ticks. Decode TPOT is bounded
by one fused tick instead of one whole prompt, and the pool crosses
one program boundary per tick instead of two (one future ``shard_map``
seam). Same-bucket same-tick admissions form batched chunk ROWS — n
slots advance one chunk each in the same program (wave batching,
recovered). ``decode_per_chunk`` is the interleave budget — at least
that many decode dispatches separate consecutive chunk programs, and
the fused tick's own decode half (which advances every active slot)
counts as the first, so ``decode_per_chunk - 1`` chunkless ticks run
in between (the two-program tick's pacing, preserved).
``chunk_autotune=True`` (with ``slo_tpot_s``) picks the largest chunk
bucket whose predicted fused-tick time fits under the TPOT SLO,
re-evaluated per admission so the compile set stays finite. Chunked
prefill is a *scheduling* change only: tokens are pinned identical to
the monolithic wave (greedy+sampled × bf16+int8, prefix-hit and
preempt-resume cases — tests/test_serving_chunked.py).

Speculative decoding (``speculate=SpecConfig(...)``; docs/SERVING.md
§Speculative decoding): after batched heads, int8 KV, paging and
chunked prefill, decode's remaining cost is its *serial step count* —
every token pays one full weight stream. With speculation armed, each
tick verifies k proposed tokens per active slot in ONE
``fused_paged_verify_step`` dispatch (the kernel's KV chunk walk plus a
k-token causal tail) and commits the longest proposal prefix that
matches the engine's OWN samples — token-exact acceptance off each
slot's ``fold_in(seed, count)`` stream, so committed tokens are
bit-identical to the non-speculative engine (and to isolated
``generate``; tests/test_serving_spec.py pins greedy+sampled ×
bf16+int8, through preempt/resume and snapshot/restore). Proposals come
from a device-side per-slot n-gram matcher (no extra model, zero
steady-state H2D) or a draft model riding its own block tables over
the same paged machinery.
"""

import heapq
import json
import logging
import numbers
import os
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.fused_decode import (mp_gather_kv_lastdim,
                                         mp_local_kv_lastdim)
from paddle_tpu.serving.pool import (SCRATCH_BLOCK, BlockPool,
                                     HostBlockStore, PoolExhausted,
                                     PrefixCache)
from paddle_tpu.serving.spec import SpecConfig

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["PRIORITIES", "Rejected", "Request", "RequestResult",
           "RestoreError", "ServingEngine", "SpecConfig",
           "ENGINE_SNAPSHOT_SCHEMA"]

ENGINE_SNAPSHOT_SCHEMA = "paddle_tpu.engine_snapshot/v1"

# token-count buckets for the serving.chunk_tokens histogram (chunk
# sizes are powers-of-two-ish token counts, not latencies)
_CHUNK_SIZE_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

# rows-per-chunk-dispatch buckets for serving.chunk_rows (small integer
# counts — n same-bucket prefilling slots advancing in one fused tick)
_CHUNK_ROWS_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16)

# chunk-autotune probing cadence: every this many tuned admissions with
# an unmeasured next-larger bucket, pick it once so its tick-time EWMA
# gets a real observation (see _autotune_chunk)
_CHUNK_PROBE_EVERY = 8
# per-bucket probe budget: a probe's own ticks are COLD (fresh
# programs), and cold ticks never feed the EWMAs — only a repeat of
# the same shape dispatches warm and records. Two tries buys that
# repeat; a bucket whose shapes never recur stops costing compile
# chains after the budget instead of re-probing forever
_CHUNK_PROBE_TRIES = 2

# accepted-proposal-length buckets for serving.spec_accepted_len (small
# integer counts, not latencies — k rarely exceeds 8)
_SPEC_LEN_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16)

#: admission classes, lowest to highest. The queue orders by (priority,
#: submit order); preemption only ever evicts a STRICTLY lower class, so
#: two requests of the same class can never ping-pong each other.
PRIORITIES = ("low", "normal", "high")
_PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}

# module-wide request-id source. Locked (concurrent submitter threads
# must never mint the same id — results are keyed by it) and bumpable:
# restore() pushes it past every id a snapshot carries so a restored
# engine's NEW submissions cannot collide with re-admitted ones.
_req_id_state = {"next": 0}
_req_id_lock = threading.Lock()


def _program_handle(jitted, bound):
    """Wrap a jitted program with its bound leading arguments and
    attach the ``.jitted``/``.bound`` audit handle
    ``analysis.runtime.donation_report`` lowers the REAL program
    through (docs/ANALYSIS.md §Donation report). ``bound`` is a
    thunk so the handle tracks state swaps (restore/recover)."""
    fn = lambda *a: jitted(*bound(), *a)    # noqa: E731
    fn.jitted, fn.bound = jitted, bound
    return fn


def _next_req_id() -> int:
    with _req_id_lock:
        v = _req_id_state["next"]
        _req_id_state["next"] = v + 1
        return v


def _note_req_id(rid: int):
    """Keep the auto-id source ahead of every explicitly assigned id."""
    with _req_id_lock:
        if rid >= _req_id_state["next"]:
            _req_id_state["next"] = rid + 1


class Rejected(RuntimeError):
    """Typed load-shed signal raised by :meth:`ServingEngine.submit`.

    ``reason`` is machine-readable: ``queue_full`` (bounded queue at
    capacity, no lower-priority victim to displace) or
    ``deadline_infeasible`` (the EWMA capacity estimate says the
    request's deadline expires before its first token). Each rejection
    also increments ``serving.rejected{reason=...}``."""

    def __init__(self, reason: str, msg: str):
        super().__init__(msg)
        self.reason = reason


class DrainTimeout(RuntimeError):
    """Typed drain-deadline failure: ``Router.drain(timeout_s=...)`` /
    ``drain_replica(..., timeout_s=...)`` raise this instead of
    spinning when a replica stops answering inside the wall-clock
    budget. ``replica`` names the stuck replica slot (None when the
    stall is tier-wide) and ``queue_depth`` is the work still parked
    behind it — the two facts an operator needs to decide between
    waiting longer and killing the worker."""

    def __init__(self, msg: str, *, replica=None, queue_depth: int = 0):
        super().__init__(msg)
        self.replica = replica
        self.queue_depth = int(queue_depth)


class RestoreError(ValueError):
    """Typed :meth:`ServingEngine.restore` failure.

    ``reason`` is machine-readable: ``schema`` (the payload is not an
    engine snapshot), ``model_fingerprint`` (the snapshot was taken on
    a different architecture/layer-count/KV-width than the model being
    restored onto — resuming would decode garbage KV), or
    ``draft_model_missing`` (the snapshot armed the draft-model
    proposer, whose model does not serialize — pass
    ``speculate=SpecConfig(..., draft_model=...)`` as a restore
    override). Subclasses ``ValueError`` so pre-existing callers that
    caught that keep working; new callers (the serving router's
    failover path) branch on ``reason`` instead of parsing messages."""

    def __init__(self, reason: str, msg: str):
        super().__init__(msg)
        self.reason = reason


class Request:
    """One generation request.

    Sampling *shape* knobs (temperature/top_k/top_p/eos) live on the
    engine — they are baked into the one shared decode program. Per
    request: the prompt, the token budget, the RNG ``seed`` (defaults to
    a fresh engine-assigned seed; pass the seed an isolated
    ``generate(..., request_seeds=[seed])`` call would use to reproduce
    it exactly), an optional wall-clock ``deadline_s`` measured from
    submit (queue wait included) — on expiry the request retires with
    the tokens it has, mirroring ``generate(deadline_s=...)`` — and a
    ``priority`` class (one of :data:`PRIORITIES`) that orders
    admission and decides who sheds/preempts whom under overload.

    Every argument is validated HERE with a plain ``ValueError`` — a
    bad budget or unknown priority must not surface as an opaque
    failure deep inside the scheduler's ``_admit``.
    """

    __slots__ = ("request_id", "prompt", "max_new_tokens", "seed",
                 "deadline_s", "priority", "trace_id", "_t_submit",
                 "_t_first", "_resume_tokens", "_seq")

    def __init__(self, prompt, max_new_tokens: int = 32,
                 seed: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 priority: str = "normal",
                 request_id: Optional[int] = None,
                 trace_id: Optional[str] = None):
        # tpu-lint: allow(host-sync): API boundary — prompts are host ids
        prompt = np.asarray(prompt)
        if not np.issubdtype(prompt.dtype, np.integer):
            raise ValueError(
                f"prompt must hold integer token ids, got dtype "
                f"{prompt.dtype}")
        self.prompt = prompt.astype(np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if isinstance(max_new_tokens, bool) \
                or not isinstance(max_new_tokens, numbers.Integral):
            raise ValueError(
                f"max_new_tokens must be an int, got "
                f"{type(max_new_tokens).__name__}")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        self.max_new_tokens = int(max_new_tokens)
        if seed is not None and (isinstance(seed, bool)
                                 or not isinstance(seed, numbers.Integral)):
            raise ValueError(f"seed must be an int or None, got "
                             f"{type(seed).__name__}")
        self.seed = None if seed is None else int(seed)
        if deadline_s is not None:
            if isinstance(deadline_s, bool) \
                    or not isinstance(deadline_s, numbers.Real):
                raise ValueError(f"deadline_s must be a number or None, "
                                 f"got {type(deadline_s).__name__}")
            if not deadline_s > 0:
                raise ValueError(
                    f"deadline_s must be > 0 (it is a wall-clock budget "
                    f"from submit), got {deadline_s}")
            deadline_s = float(deadline_s)
        self.deadline_s = deadline_s
        if priority not in _PRIORITY_RANK:
            raise ValueError(f"unknown priority {priority!r}; one of "
                             f"{PRIORITIES}")
        self.priority = priority
        if request_id is None:
            self.request_id = _next_req_id()
        else:
            self.request_id = int(request_id)
            _note_req_id(self.request_id)
        # causal trace id: minted ONCE at first construction, carried
        # verbatim through preempt/resume, snapshot/restore, and router
        # failover migration — every journal event / span / timeline
        # fragment a request produces anywhere in the tier keys on it
        # (docs/OBSERVABILITY.md §Request traces)
        if trace_id is None:
            self.trace_id = uuid.uuid4().hex[:16]
        else:
            self.trace_id = str(trace_id)
        self._t_submit: Optional[float] = None
        # preempt/resume state: the generated-so-far tokens a requeued
        # request re-prefills from (None = fresh), and the original
        # first-token timestamp so TTFT survives a preemption
        self._resume_tokens: Optional[List[int]] = None
        self._t_first: Optional[float] = None
        self._seq: int = 0          # engine submit ordinal (FIFO tiebreak)

    @property
    def rank(self) -> int:
        return _PRIORITY_RANK[self.priority]


class RequestResult:
    """Terminal state of a request. ``tokens`` are the generated ids
    (eos included when hit); ``gen_len`` counts tokens before the first
    eos — the same accounting ``generate(return_lengths=True)`` reports.
    ``finish`` is one of ``eos`` / ``length`` / ``deadline`` / ``shed``
    (a queued request displaced by a higher-priority submit under a
    full bounded queue — ``tokens`` is empty, ``ttft_s`` None)."""

    __slots__ = ("request_id", "prompt", "tokens", "gen_len", "finish",
                 "ttft_s", "tpot_s", "prefix_hit_blocks", "trace_id")

    def __init__(self, request_id, prompt, tokens, gen_len, finish,
                 ttft_s, tpot_s, prefix_hit_blocks, trace_id=None):
        self.request_id = request_id
        self.trace_id = trace_id
        self.prompt = prompt
        # tpu-lint: allow(host-sync): generated tokens are a host list
        self.tokens = np.asarray(tokens, np.int32)
        self.gen_len = int(gen_len)
        self.finish = finish
        self.ttft_s = ttft_s
        self.tpot_s = tpot_s
        self.prefix_hit_blocks = prefix_hit_blocks

    @property
    def ids(self) -> np.ndarray:
        """prompt + generated tokens, the ``generate`` output row."""
        return np.concatenate([self.prompt, self.tokens])


class _Slot:
    __slots__ = ("req", "tok", "pos", "count", "tokens", "blocks", "ntab",
                 "worst_blocks", "t_first", "deadline_at",
                 "prefix_hit_blocks", "feed", "resume",
                 "prefilling", "filled", "R", "hits", "dblocks")

    def __init__(self, req: Request, worst_blocks: int,
                 prefix_hit_blocks: int, feed: np.ndarray,
                 resume: Optional[List[int]]):
        # snapshot-coverage (docs/SERVING.md §Snapshot contract): a
        # slot's tokens/seed ARE its complete resumable state — the
        # cursor and KV fields below are volatile by design, rebuilt
        # when restore() re-admits the request through the resume path
        self.req = req
        # tpu-lint: volatile(reconstructed from tokens by resume replay)
        self.tok = 0            # last sampled, kv not yet appended
        # tpu-lint: volatile(reconstructed from tokens by resume replay)
        self.pos = 0            # append position of the next decode step
        # tpu-lint: volatile(count == len(tokens); resume re-derives it)
        self.count = 0          # tokens generated so far
        self.tokens: List[int] = []
        # tpu-lint: volatile(pool KV never survives a crash by design)
        self.blocks: List[int] = []     # owned pool refs (shared + private)
        # tpu-lint: volatile(block-table depth; re-derived at re-admission)
        self.ntab = 0                   # blocks allocated for this slot
        self.worst_blocks = worst_blocks
        # tpu-lint: volatile(wall-clock; TTFT survives via req._t_first)
        self.t_first: Optional[float] = None
        # tpu-lint: volatile(re-anchored from deadline_remaining_s)
        self.deadline_at: Optional[float] = None
        self.prefix_hit_blocks = prefix_hit_blocks
        # what the prefill program runs over: the PROMPT (for fresh and
        # resumed admissions alike — a resume's generated tokens replay
        # through the decode step program afterwards, _replay_resume;
        # the final generated token is never appended — it becomes the
        # next decode step's input, exactly where an uninterrupted run
        # left off)
        self.feed = feed
        self.resume = resume            # generated-so-far tokens, or None
        # chunked-prefill cursor state (chunk_tokens engines): while
        # `prefilling`, `filled` counts the feed tokens whose KV is
        # already written (starts at the prefix depth R), and `hits`
        # keeps the prefix-cache entries chunk 0 adopts (the int8
        # resident KV carry lives on the slot's _ChunkGroup, not
        # here). A prefilling slot stays OUT of the decode batch (its
        # mirror table row points at scratch) until its last chunk
        # samples the first token.
        # tpu-lint: volatile(restore re-prefills from tokens; the
        # serialized chunk cursor is informational)
        self.prefilling = False
        # tpu-lint: volatile(chunk cursor; re-prefill restarts it)
        self.filled = 0
        # tpu-lint: volatile(prefix depth; re-probed at re-admission)
        self.R = 0                      # prefix-hit depth in tokens
        # tpu-lint: volatile(prefix-cache refs; re-probed at re-admission)
        self.hits = None
        # draft-proposer block table rows (speculative engines with a
        # draft model: the draft's KV pages for this slot)
        # tpu-lint: volatile(draft pages rebuilt at resume adoption)
        self.dblocks: List[int] = []


class _Parked:
    """One swapped-out request's host-tier KV (docs/SERVING.md
    §Hierarchical KV): the gathered device buffer until the background
    drain lands it in the ``HostBlockStore`` (``dev`` → ``host_ids``),
    plus the cursor state a swap-in rebuilds the slot from WITHOUT a
    prefill program or a replay dispatch — the generated-position KV
    comes back bitwise. Parked KV is a resume accelerator, not durable
    state: the queue's serialized resume tokens remain the crash story
    (restore re-prefills where a live engine would swap in)."""

    __slots__ = ("rid", "dev", "host_ids", "n", "scales", "pos", "tok",
                 "count", "tokens", "worst_blocks", "prefix_hit_blocks",
                 "t_swap")

    def __init__(self, rid, dev, n, scales, pos, tok, count, tokens,
                 worst_blocks, prefix_hit_blocks):
        self.rid = rid
        self.dev = dev          # gathered (L, n_pad, BT, 2dkv) device buf
        self.host_ids: Optional[List[int]] = None
        self.n = int(n)         # real block count (rest of dev is pad)
        self.scales = scales    # int8 per-slot scale row copy, or None
        self.pos = int(pos)
        self.tok = int(tok)
        self.count = int(count)
        self.tokens = tokens    # generated-so-far (owned copy)
        self.worst_blocks = int(worst_blocks)
        self.prefix_hit_blocks = int(prefix_hit_blocks)
        self.t_swap = time.perf_counter()   # for the prefetch EWMA


class _ChunkGroup:
    """A batch of same-bucket prefilling slots advancing ONE chunk per
    fused tick (the batched-chunk-rows half of the one-program tick):
    every row shares the prefix depth ``R``, the chunk size ``chunk``
    (the autotuner's per-admission pick) and the padded feed bucket
    ``C_pad = R + ceil((P-R)/chunk)*chunk``, so the whole group's
    cursors advance in lockstep and one fused-tick program serves all
    ``n`` rows — same-tick same-shape admissions recover the wave
    batching the n=1 chunk FIFO serialized.

    The group's inputs are DEVICE-RESIDENT from creation (feed ids,
    block-id table, last-token indices, seeds, int8 valid lengths and
    prefix copies), so steady mid-prefill fused ticks re-dispatch with
    zero H2D uploads. On int8 pools ``carry`` is the resident bf16 KV
    buffer (L, n, C_pad, 2dkv) the chunk programs RMW in place
    (donated — ``analysis.runtime.donation_report`` pins the
    aliasing); bf16 pools need NO carry at all — every completed
    chunk's blocks are already in the pool, so the next chunk gathers
    its processed prefix straight from pool blocks."""

    __slots__ = ("rows", "R", "chunk", "C_pad", "int8", "carry",
                 "dev_ids", "dev_bids", "dev_last", "dev_seeds",
                 "dev_valid", "dev_prefix")

    def __init__(self, rows, R, chunk, C_pad, int8):
        self.rows = rows            # [(slot_idx, slot)]
        self.R = int(R)
        self.chunk = int(chunk)
        self.C_pad = int(C_pad)
        self.int8 = int8
        # tpu-lint: volatile(device KV carry; restore re-prefills)
        self.carry = None
        self.dev_ids = self.dev_bids = None
        self.dev_last = self.dev_seeds = None
        self.dev_valid = self.dev_prefix = None

    @property
    def n(self) -> int:
        return len(self.rows)

    @property
    def start(self) -> int:
        """The group's chunk cursor (rows advance in lockstep)."""
        return self.rows[0][1].filled

    @property
    def kind(self) -> str:
        return "last" if self.start + self.chunk >= self.C_pad else "mid"

    def args(self):
        """The chunk half's traced arguments at the current cursor —
        every one device-resident (the steady-tick 0-H2D invariant)."""
        start, last = self.start, self.kind == "last"
        a = []
        if self.int8 and start > self.R:
            a.append(self.carry)
        a += [self.dev_ids, self.dev_bids]
        if self.dev_prefix is not None and start == self.R:
            a.append(self.dev_prefix)
        if last:
            a += [self.dev_last, self.dev_seeds]
            if self.int8:
                a.append(self.dev_valid)
        return a


class _PriorityQueue:
    """Priority-then-FIFO request queue: a heap ordered by
    (-priority_rank, submit_seq) with lazy deletion. push/pop are
    O(log n); the displacement-victim scan and the estimator walk are
    O(n) over the raw heap (``items()``, no sort — neither cares about
    order); only ``__iter__`` (snapshots) pays a sort."""

    def __init__(self):
        self._heap: List = []           # (neg_rank, seq, req)
        self._removed = set()           # request_ids shed before pop
        self._live = 0

    def __len__(self) -> int:
        return self._live

    def __bool__(self) -> bool:
        return self._live > 0

    def push(self, req: Request):
        heapq.heappush(self._heap, (-req.rank, req._seq, req))
        self._live += 1

    def _prune(self):
        while self._heap and self._heap[0][2].request_id in self._removed:
            self._removed.discard(heapq.heappop(self._heap)[2].request_id)

    def peek(self) -> Optional[Request]:
        self._prune()
        return self._heap[0][2] if self._heap else None

    def pop(self) -> Request:
        self._prune()
        self._live -= 1
        return heapq.heappop(self._heap)[2]

    def remove(self, req: Request):
        self._removed.add(req.request_id)
        self._live -= 1

    def items(self):
        """Live requests in arbitrary (heap) order — the O(n) walk for
        order-insensitive consumers (victim scan, TTFT estimator)."""
        return (r for _, _, r in self._heap
                if r.request_id not in self._removed)

    def __iter__(self):
        """Live requests in pop order (snapshots). seq is unique per
        engine, so sorting never compares requests."""
        return (r for _, _, r in sorted(self._heap, key=lambda e: e[:2])
                if r.request_id not in self._removed)

    def lowest_below(self, rank: int) -> Optional[Request]:
        """The displacement victim: lowest-priority, most-recently
        queued request STRICTLY below ``rank``; None when every queued
        request is at least ``rank``."""
        best = None
        for r in self.items():
            if r.rank >= rank:
                continue
            if best is None or (r.rank, -r._seq) < (best.rank, -best._seq):
                best = r
        return best


class _Ewma:
    """One exponentially-weighted moving average (the engine's capacity
    estimator state — fed the SAME per-segment wall times the PR 7
    ``serving.step_*_s`` histograms observe)."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = 0.25):
        self.alpha = float(alpha)
        self.value: Optional[float] = None

    def update(self, x: float):
        self.value = (float(x) if self.value is None
                      else (1.0 - self.alpha) * self.value
                      + self.alpha * float(x))


def _swap_bucket(n: int) -> int:
    """Power-of-two bucket for whole-block gather/scatter widths —
    bounds the swap-path compile set to O(log max_blocks_per_slot)
    programs (pad entries target the scratch block)."""
    m = 1
    while m < n:
        m *= 2
    return m


class ServingEngine:
    """Continuous-batching decode over a paged KV pool.

    ``max_slots`` is the decode batch width (one fused dispatch serves
    all active slots). The pool holds ``num_blocks`` blocks of
    ``block_tokens`` tokens each — sized directly (``num_blocks``), by
    byte budget (``pool_bytes`` / the per-block byte cost at the cache
    element size: 1 for int8, 2 for bf16), or defaulted to worst case
    (every slot filled to ``max_seq_len``). Admission reserves each
    request's worst-case blocks (prompt + max_new) so lazy per-step
    block allocation can never fail mid-flight; physical blocks are
    still allocated lazily, so pool-usage gauges track real occupancy.

    ``cache_dtype=jnp.int8`` enables the int8 KV pool: each request's
    prefill is its own calibration pass (per-SLOT scales — an isolated
    b=1 ``generate`` computes the same scales, which is what keeps int8
    parity token-exact).

    Observability: every ``step()`` is wall-timed in four segments
    (``serving.step_*_s`` histograms), per-request TTFT/TPOT land in
    the ``serving.ttft_s``/``serving.tpot_s`` quantile sketches, and a
    flight-recorder ring (last ``flight_capacity`` step events,
    auto-dumped to ``flight_dump_path`` on a fired fault /
    ``PoolExhausted`` / deadline retirement / preemption / shed) keeps
    the postmortem trail — docs/OBSERVABILITY.md has the event format.

    Overload control (all off by default — the unbounded engine is the
    PR 5 behavior): ``max_queue`` bounds the queue (a submit against a
    full queue displaces a strictly lower-priority queued victim, else
    raises :class:`Rejected`); ``shed_infeasible=True`` rejects
    deadline-carrying requests whose deadline the EWMA capacity
    estimate says cannot reach a first token. Priority preemption is
    always armed but only ever fires across *different* priority
    classes, so all-default-priority workloads never preempt.

    ``chunk_tokens`` (None = monolithic wave prefill, the PR 5
    behavior) arms chunked prefill: prompts are prefilled
    ``chunk_tokens`` tokens per program (must be a multiple of
    ``block_tokens``), at most one chunk per tick. A chunk tick is ONE
    fused program — the chunk AND the decode step for every
    decode-ready slot coscheduled, bf16 mid chunks gathering their
    processed prefix from the pool and int8 prefills threading a
    resident bf16 carry (donated, aliased in-place) — so a long
    prompt never stalls active decode slots for more than one fused
    tick, and same-bucket
    same-tick admissions advance as batched chunk rows in the same
    program. ``decode_per_chunk`` decode dispatches are guaranteed
    between consecutive chunk programs while decode-ready slots exist
    — the fused tick's own decode half counts as the first, so
    ``decode_per_chunk - 1`` chunkless ticks separate chunk ticks. Fused-tick programs are keyed by the chunk bucket
    (kind, cursor, rows, feed bucket, chunk size) — fixed buckets, so
    the compile set stays small and exactly pinned
    (tests/test_analysis.py). ``chunk_autotune=True`` (requires
    ``slo_tpot_s``) picks each admission's chunk size: the largest
    power-of-two bucket (anchored at ``chunk_tokens``) whose predicted
    fused-tick time fits under the TPOT SLO.

    ``speculate=SpecConfig(...)`` (None = plain per-token decode) arms
    speculative decoding: every decode tick verifies k proposed tokens
    per active slot in ONE ``fused_paged_verify_step`` dispatch and
    commits the longest proposal prefix matching the engine's own
    samples — 1..k+1 tokens per dispatch, bit-identical to the
    non-speculative engine (docs/SERVING.md §Speculative decoding).
    Proposals come from a device-side n-gram matcher
    (``proposer="ngram"``, no extra model) or a draft model
    (``proposer="draft"``) riding its own block tables over the same
    paged machinery.

    ``sanitize=True`` (debug; docs/ANALYSIS.md) arms the dispatch
    sanitizer: every steady-state decode dispatch runs under
    ``analysis.runtime.sanitize()`` — zero H2D transfers, zero
    recompiles, or it RAISES at the offending step.
    ``stats["sanitized_steps"]`` counts the guarded dispatches.

    ``mesh=``/``layout=`` (docs/SERVING.md §Tensor-parallel replicas)
    shard THIS replica over the ``{mp, fsdp}`` mesh axes: attention
    heads and FFN lanes column-parallel over ``mp`` with the paged KV
    pool split on the head dim (``serving.layout.ServingLayout``),
    stacked weights layer-sharded over ``fsdp`` and gathered at use.
    Every program runs under full-manual ``jax.shard_map`` through one
    seam (:meth:`_wrap_program`); sampling and scheduling stay
    replicated, so tokens are BIT-IDENTICAL to the mp=1 engine and
    snapshots stay mesh-free. ``mesh=None`` (default) is exactly the
    single-chip engine.
    """

    def __init__(self, model, *, max_slots: int = 4,
                 block_tokens: int = 128, num_blocks: Optional[int] = None,
                 pool_bytes: Optional[int] = None, max_seq_len: int = 1024,
                 cache_dtype=jnp.bfloat16, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 prefix_caching: bool = True,
                 prefix_cache_blocks: int = 256,
                 flight_capacity: int = 256,
                 flight_dump_path: Optional[str] = None,
                 metrics_labels: Optional[Dict] = None,
                 max_queue: Optional[int] = None,
                 shed_infeasible: bool = False,
                 chunk_tokens: Optional[int] = None,
                 decode_per_chunk: int = 1,
                 chunk_autotune: bool = False,
                 slo_tpot_s: Optional[float] = None,
                 speculate: Optional[SpecConfig] = None,
                 offload: bool = False,
                 host_pool_blocks: Optional[int] = None,
                 offload_prefetch: int = 2,
                 sanitize: bool = False,
                 mesh=None, layout=None,
                 state: Optional[Dict] = None):
        from paddle_tpu.inference import _inference_state
        from paddle_tpu.observability.flight import FlightRecorder
        from paddle_tpu.observability.registry import registry

        self.model = model
        self._state = state if state is not None else _inference_state(model)
        meta = (model.fused_decode_plan(self._state, probe=True)
                if hasattr(model, "fused_decode_plan") else None)
        if meta is None:
            raise ValueError(
                "ServingEngine needs a fused_decode_plan-eligible model "
                "(llama/gpt); this model/config cannot ride the paged "
                "kernel")
        self.arch = meta.get("arch", "llama")
        if self.arch not in ("llama", "gpt"):
            raise ValueError(
                f"paged serving supports arch llama/gpt, got {self.arch!r}")
        blocks_plan = meta.get("blocks")
        if blocks_plan is not None and blocks_plan.get("q_split", 1) != 1:
            raise ValueError(
                "paged serving does not support the q-split (big-model) "
                "weight-streaming regime yet")
        self.meta = meta
        self.kv_int8 = jnp.dtype(cache_dtype) == jnp.int8
        if not self.kv_int8 and jnp.dtype(cache_dtype).itemsize != 2:
            raise ValueError(
                f"cache_dtype must be bf16-width or int8, got "
                f"{jnp.dtype(cache_dtype).name}")
        self.cache_dtype = jnp.int8 if self.kv_int8 else cache_dtype
        if max_seq_len % block_tokens:
            raise ValueError(
                f"max_seq_len {max_seq_len} must be a multiple of "
                f"block_tokens {block_tokens}")
        self.block_tokens = int(block_tokens)
        self.max_seq_len = int(max_seq_len)
        self.max_slots = int(max_slots)
        self.max_blocks_per_slot = max_seq_len // block_tokens

        L = self._num_layers = self._count_layers()
        nkv, hd = meta["num_kv_heads"], meta["head_dim"]
        self._dkv = nkv * hd

        # ---- tensor-parallel replica (docs/SERVING.md §Tensor-parallel
        # replicas): mesh + ServingLayout shard THIS replica over
        # {mp, fsdp}. mesh None (or size 1) is the exact pre-mp path:
        # every program compiles byte-identical to the single-chip
        # engine (tests/test_serving_mp.py pins the program set).
        if layout is not None and mesh is None:
            mesh = layout.mesh
        if mesh is not None and getattr(mesh, "size", 1) == 1:
            mesh = None
            layout = None
        if mesh is not None:
            from paddle_tpu.serving.layout import ServingLayout
            if layout is None:
                layout = ServingLayout(mesh)
            elif layout.mesh is not mesh:
                raise ValueError(
                    "layout was built for a different mesh; pass "
                    "matching mesh/layout (or just the layout)")
            layout.validate(num_heads=meta["num_heads"],
                            num_kv_heads=nkv, num_layers=L)
        self.mesh = mesh
        self.layout = layout
        self._mp = layout.mp if layout is not None else 1
        self._mp_axis = layout.mp_axis if layout is not None else None
        self._fsdp_axis = (layout.fsdp_axis if layout is not None
                           else None)
        if layout is not None:
            # commit the full state replicated so every program input
            # already lives on the mesh (no implicit transfer at
            # dispatch — the 0-H2D steady-tick pin holds under mp too)
            self._state = layout.place_replicated(self._state)
        bpb = self.block_bytes = (
            L * block_tokens * 2 * self._dkv
            * (1 if self.kv_int8 else 2))
        if num_blocks is None:
            if pool_bytes is not None:
                num_blocks = max(2, int(pool_bytes) // bpb)
            else:   # worst case: every slot filled to max_seq_len
                num_blocks = max_slots * self.max_blocks_per_slot + 1
        # tpu-lint: volatile(occupancy re-derives as restored requests
        # re-admit; num_blocks rides the snapshot config)
        self.pool = BlockPool(num_blocks, block_tokens)
        # tpu-lint: volatile(device KV never survives a crash by design
        # — restore re-prefills prompts and replays generated tokens)
        self.kv_pool = jnp.zeros(
            (L, num_blocks, block_tokens, 2 * self._dkv), self.cache_dtype)
        if layout is not None:
            # head-dim sharded: each shard's block-table walk reads only
            # its own heads' [k_s|v_s] lanes (zeros are permutation-
            # symmetric, so placing the canonical zeros is exact)
            self.kv_pool = layout.place(self.kv_pool, layout.pool_spec())
        # tpu-lint: volatile(rebuilds from traffic; snapshot keys are
        # postmortem info only)
        self.prefix_cache = (PrefixCache(self.pool, prefix_cache_blocks)
                             if prefix_caching else None)

        # ---- hierarchical KV: host-RAM block tier (docs/SERVING.md
        # §Hierarchical KV). offload=True arms the swap paths: a
        # preemption GATHERS the victim's blocks to host RAM instead of
        # freeing them (background D2H drain overlapped with serving
        # ticks), and resume SCATTERS them back — the generated-position
        # KV is restored bitwise, so the token-exact resume path runs
        # zero replay dispatches when the blocks survived.
        self.offload = bool(offload)
        if host_pool_blocks is not None and host_pool_blocks < 1:
            raise ValueError(f"host_pool_blocks must be >= 1 or None, "
                             f"got {host_pool_blocks}")
        self.offload_prefetch = int(offload_prefetch)
        if self.offload_prefetch < 0:
            raise ValueError(f"offload_prefetch must be >= 0, got "
                             f"{offload_prefetch}")
        # tpu-lint: volatile(host KV never survives a crash by design —
        # a restored engine's parked requests re-admit down the
        # token-exact re-prefill+replay path, exactly like slot KV;
        # host_pool_blocks rides the snapshot config)
        self.host_store = (HostBlockStore(
            host_pool_blocks if host_pool_blocks is not None
            else 4 * num_blocks) if self.offload else None)
        # in-flight and host-resident parked swap records, keyed by
        # request_id: _Parked carries the gathered device buffer until
        # the background drain lands it in host_store, then the host ids
        # tpu-lint: volatile(parked KV is a resume ACCELERATOR — the
        # queue's serialized resume tokens are the durable state, so
        # restore simply re-prefills where a live engine would swap in)
        self._parked: Dict[int, "_Parked"] = {}
        # tpu-lint: volatile(compiled-program cache)
        self._swap_fns: Dict = {}
        # device-staged swap-in payloads keyed by request_id (prefetch
        # landed ahead of admission) — see _offload_prefetch
        # tpu-lint: volatile(prefetch staging re-warms from host tier)
        self._staged: Dict[int, object] = {}
        # EWMA of observed swap-in staging wall seconds: the prefetch
        # policy's probe-and-observe estimate (chunk_autotune pattern)
        # of how far ahead of admission staging must start
        # tpu-lint: volatile(prefetch estimator re-learns)
        self._ewma_swap_s = _Ewma()

        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token_id = eos_token_id
        self.seed = int(seed)
        self._seeds_issued = 0
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1 or None, got "
                             f"{max_queue}")
        self.max_queue = None if max_queue is None else int(max_queue)
        self.shed_infeasible = bool(shed_infeasible)
        if chunk_tokens is not None:
            chunk_tokens = int(chunk_tokens)
            if chunk_tokens < block_tokens or chunk_tokens % block_tokens:
                raise ValueError(
                    f"chunk_tokens {chunk_tokens} must be a positive "
                    f"multiple of block_tokens {block_tokens} (chunks "
                    f"append block-aligned KV)")
        self.chunk_tokens = chunk_tokens
        if decode_per_chunk < 1:
            raise ValueError(f"decode_per_chunk must be >= 1, got "
                             f"{decode_per_chunk}")
        self.decode_per_chunk = int(decode_per_chunk)
        if slo_tpot_s is not None and not slo_tpot_s > 0:
            raise ValueError(f"slo_tpot_s must be > 0 or None, got "
                             f"{slo_tpot_s}")
        self.slo_tpot_s = None if slo_tpot_s is None else float(slo_tpot_s)
        self.chunk_autotune = bool(chunk_autotune)
        if self.chunk_autotune and (chunk_tokens is None
                                    or self.slo_tpot_s is None):
            raise ValueError(
                "chunk_autotune needs both chunk_tokens (the cold "
                "default / ladder anchor) and slo_tpot_s (the TPOT-SLO "
                "headroom the tuner fits chunks under)")
        # the autotuner's current pick (== chunk_tokens until a warm
        # EWMA moves it); what estimated_ttft_s prices chunks at
        # tpu-lint: volatile(autotuner re-learns; config carries knobs)
        self._chunk_choice = chunk_tokens
        # per-bucket fused-tick wall-time EWMAs (the measured refinement
        # over the per-token linear prediction)
        # tpu-lint: volatile(capacity estimator re-learns)
        self._chunk_time_ewma: Dict[int, _Ewma] = {}
        # tpu-lint: volatile(probe cadence counter)
        self._chunk_probe_wait = 0
        # tpu-lint: volatile(probe budget re-learns after restore)
        self._chunk_probe_tries: Dict[int, int] = {}
        self._closed = False

        from paddle_tpu.ops import rope as rope_ops
        self._cos_tab, self._sin_tab = rope_ops.rope_cos_sin(
            max_seq_len, hd, base=meta["rope_base"])
        if layout is not None:
            # closed-over rope tables must be mesh-committed too, or
            # every program would mix mesh and single-device operands
            self._cos_tab, self._sin_tab = layout.place_replicated(
                (self._cos_tab, self._sin_tab))

        # host mirrors of the per-slot device state — all volatile:
        # resume admission rebuilds every row from the serialized
        # (tokens, seed) resumable requests
        ms = self.max_slots
        # tpu-lint: volatile(rebuilt by resume admission)
        self._tables = np.full((ms, self.max_blocks_per_slot),
                               SCRATCH_BLOCK, np.int32)
        # tpu-lint: volatile(rebuilt by resume admission)
        self._positions = np.zeros(ms, np.int32)
        # tpu-lint: volatile(rebuilt by resume admission)
        self._toks = np.zeros(ms, np.int32)
        # tpu-lint: volatile(rebuilt by resume admission)
        self._seeds = np.zeros(ms, np.uint32)
        # tpu-lint: volatile(rebuilt by resume admission)
        self._counts = np.zeros(ms, np.int32)
        # tpu-lint: volatile(int8 calibration reproduces scales exactly)
        self._kv_scales = np.ones((L, ms, 2 * self._dkv), np.float32)

        # ---- speculative decoding (docs/SERVING.md §Speculative) ----
        self.speculate = speculate
        self._spec_k = 0
        # tpu-lint: volatile(compiled-program cache)
        self._verify_fns: Dict[int, object] = {}   # keyed by tail k
        # tpu-lint: volatile(compiled-program cache)
        self._draft_fns: Dict[int, object] = {}
        # tpu-lint: volatile(device constants, rebuilt per tail width)
        self._prop_zeros: Dict = {}     # ngram: per-k proposal reset
        # tpu-lint: volatile(device constants, rebuilt per tail width)
        self._nprop_fulls: Dict = {}    # draft: per-k full-proposal consts
        # per-slot adaptive k state (SpecConfig(adaptive=True)): the
        # device-side proposal cap, its host mirror, the per-slot k and
        # acceptance EWMAs, and the tick's effective tail width (max k
        # over active slots — one batched program serves every slot)
        # tpu-lint: volatile(adaptive k restarts at the configured k —
        # acceptance re-learns after restore, documented in SERVING.md)
        self._spec_cap = None
        # tpu-lint: volatile(device twin; re-uploads on dirty ticks)
        self._dev_cap = None
        # tpu-lint: volatile(adaptive k restarts at the configured k)
        self._spec_k_slot = None
        # tpu-lint: volatile(acceptance EWMA re-learns after restore)
        self._spec_acc_ewma = None
        # tpu-lint: volatile(adapt cadence counter)
        self._spec_adapt_tick = 0
        # tpu-lint: volatile(tail-width change detector)
        self._last_spec_k = None
        # tpu-lint: volatile(per-tick effective tail width)
        self._spec_k_eff = 0
        # tpu-lint: volatile(re-primed from committed tokens at adoption)
        self._history = None            # ngram: host mirror (ms, S)
        # tpu-lint: volatile(device twin; re-uploads on dirty ticks)
        self._dev_hist = None           # ngram: device history twin
        # tpu-lint: volatile(re-primed by the next verify dispatch)
        self._dev_prop = None           # ngram: carried device proposals
        # tpu-lint: volatile(device twin; re-uploads on dirty ticks)
        self._draft_dev = None          # draft: device block-table twin
        # tpu-lint: volatile(rebuilt by resume adoption)
        self._draft_tables = None
        # tpu-lint: volatile(draft pages rebuilt at resume adoption)
        self._draft_pool_blocks = None
        # tpu-lint: volatile(draft KV re-prefills at resume adoption)
        self.draft_kv_pool = None
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_spec = None          # (proposed, accepted) this tick
        # k=0 recovery probing (SpecConfig(adaptive=True, k_min=0);
        # docs/SERVING.md §Speculative decoding): a slot parked at k=0
        # proposes nothing, so its acceptance EWMA can never observe
        # again — every `adapt_every` parked ticks the engine raises
        # the slot's cap to ONE proposal for a two-tick window so the
        # EWMA re-observes and the slot can climb back
        # tpu-lint: volatile(probe cadence counter)
        self._spec_probe_wait = 0
        # tpu-lint: volatile(in-flight probe window; restore re-probes)
        self._probe_window = 0
        # tpu-lint: volatile(in-flight probe window; restore re-probes)
        self._probe_slots: List[int] = []
        # committed tokens per active slot per decode dispatch — what
        # the TTFT estimator divides decode work by so shed_infeasible
        # doesn't over-shed when speculation multiplies tokens/tick
        # tpu-lint: volatile(capacity estimator re-learns; cold
        # convention documented on estimated_ttft_s)
        self._ewma_spec_tokens = _Ewma()
        if speculate is not None:
            if not isinstance(speculate, SpecConfig):
                raise ValueError(
                    f"speculate must be a serving.SpecConfig, got "
                    f"{type(speculate).__name__}")
            if speculate.k >= max_seq_len:
                raise ValueError(
                    f"speculate k {speculate.k} must be < max_seq_len "
                    f"{max_seq_len}")
            self._spec_k = speculate.k
            self._spec_cap = np.full(ms, speculate.k, np.int32)
            self._spec_k_slot = np.full(ms, speculate.k, np.int32)
            self._spec_acc_ewma = [_Ewma() for _ in range(ms)]
            if speculate.proposer == "ngram":
                # the device-side suffix matcher runs over this carried
                # committed-token buffer — uploaded only on dirty ticks
                self._history = np.zeros((ms, max_seq_len), np.int32)
                # the dirty-tick proposal reset, built ONCE per tail
                # width: immutable device constants, so a join/leave
                # tick re-arms the proposer without compiling a zeros
                # program mid-drain (the compile-set pin in
                # tests/test_analysis.py)
                self._prop_zero(speculate.k)
            else:
                from paddle_tpu.inference import _inference_state as _ist
                dm = speculate.draft_model
                self._draft_state = (speculate.draft_state
                                     if speculate.draft_state is not None
                                     else _ist(dm))
                if speculate.share_embeddings:
                    # the draft rides the target's embedding table when
                    # the shapes line up (same vocab × hidden) — one
                    # buffer instead of two, and via tied_unembed the
                    # shared table is the draft's unembedding too
                    # (docs/SERVING.md §Speculative decoding)
                    shared = self._share_draft_embeddings(
                        self._draft_state)
                    if shared is not None:
                        self._draft_state = shared
                dmeta = (dm.fused_decode_plan(self._draft_state,
                                              probe=True)
                         if hasattr(dm, "fused_decode_plan") else None)
                if dmeta is None:
                    raise ValueError(
                        "draft_model needs a fused_decode_plan-eligible "
                        "config (llama/gpt) to ride the paged kernel")
                darch = dmeta.get("arch", "llama")
                if darch not in ("llama", "gpt"):
                    raise ValueError(
                        f"draft proposer supports arch llama/gpt, got "
                        f"{darch!r}")
                dbp = dmeta.get("blocks")
                if dbp is not None and dbp.get("q_split", 1) != 1:
                    raise ValueError(
                        "draft proposer does not support the q-split "
                        "(big-model) draft regime")
                self._draft_meta = dmeta
                self._draft_arch = darch
                self._draft_layers = int(getattr(dm.cfg, "num_layers"))
                self._draft_dkv = (dmeta["num_kv_heads"]
                                   * dmeta["head_dim"])
                # the draft shares the paged-pool DESIGN with its own
                # block tables; its pool is sized worst-case (a tiny
                # model's pages are cheap) so a prefix-cache-assisted
                # target admission can never strand the draft mid-flight
                dnb = ms * self.max_blocks_per_slot + 1
                self._draft_pool_blocks = BlockPool(dnb, block_tokens)
                self.draft_kv_pool = jnp.zeros(
                    (self._draft_layers, dnb, block_tokens,
                     2 * self._draft_dkv), jnp.bfloat16)
                if layout is not None:
                    # draft compute stays fully REPLICATED under mp (a
                    # tiny model — sharding it would trade parity risk
                    # for nothing); its arrays still commit to the mesh
                    # so the draft programs' shard_map wrap is uniform
                    self._draft_state = layout.place_replicated(
                        self._draft_state)
                    self.draft_kv_pool = layout.place_replicated(
                        self.draft_kv_pool)
                self._draft_stacked = jax.jit(
                    lambda st: dm.fused_decode_plan(st)["params"])(
                        self._draft_state)
                self._draft_cos, self._draft_sin = rope_ops.rope_cos_sin(
                    max_seq_len, dmeta["head_dim"],
                    base=dmeta["rope_base"])
                if layout is not None:
                    (self._draft_stacked, self._draft_cos,
                     self._draft_sin) = layout.place_replicated(
                        (self._draft_stacked, self._draft_cos,
                         self._draft_sin))
                self._draft_tables = np.full(
                    (ms, self.max_blocks_per_slot), SCRATCH_BLOCK,
                    np.int32)
                # draft proposals always fill all k slots (per-slot
                # adaptive caps are applied inside the verify program)
                self._nprop_full(speculate.k)

        self._slots: List[Optional[_Slot]] = [None] * ms
        self._queue = _PriorityQueue()
        self._submit_seq = 0
        self.results: Dict[int, RequestResult] = {}
        # tpu-lint: volatile(re-derived as restored requests re-admit)
        self._reserved = 0      # blocks promised to in-flight slots
        # tpu-lint: volatile(compiled program)
        self._step_fn = None
        # the stacked per-layer weight copy is built ONCE here and fed to
        # the step program as a traced argument: a per-token dispatch has
        # no scan to amortize the in-trace rebuild over (generate()'s
        # decode program runs build_fused_params once per max_new_tokens
        # steps; a serving step would run it once per token)
        self._stacked = jax.jit(
            lambda st: model.fused_decode_plan(st)["params"])(self._state)
        # tpu-lint: volatile(per-leaf PartitionSpecs, derived from layout)
        self._stacked_specs = None
        if layout is not None:
            ffn_w = self._stacked.get("wg")
            layout.validate(num_heads=meta["num_heads"],
                            num_kv_heads=nkv, num_layers=L,
                            ffn=(int(ffn_w.shape[-1])
                                 if ffn_w is not None else None))
            self._stacked_specs = layout.stacked_specs(self._stacked)
            self._stacked = layout.shard_stacked(
                self._stacked, num_heads=meta["num_heads"],
                num_kv_heads=nkv, head_dim=hd)
        # device twins of the host mirrors above: positions/toks/counts
        # advance ON DEVICE inside the step program (no per-step H2D
        # uploads); a join/leave/table event marks them dirty and the
        # next step re-uploads from the host mirrors
        # tpu-lint: volatile(device twins re-upload from host mirrors)
        self._dev = None
        # tpu-lint: volatile(upload flag; restore starts dirty)
        self._dirty = True
        # tpu-lint: volatile(compiled-program cache)
        self._jit_cache: Dict = {}
        # tpu-lint: volatile(per-incarnation telemetry; registry
        # counters are the cross-restore accounting)
        self.stats = self._fresh_stats()
        # tpu-lint: volatile(per-tick report; results dict carries the
        # outcomes across a restore)
        self._finished_tick: List[int] = []
        # flight recorder: one compact event per step() into a fixed
        # ring; auto-dumped at the resilience seams when a dump path is
        # configured (fired fault / PoolExhausted / deadline retirement)
        self.flight = FlightRecorder(capacity=flight_capacity,
                                     auto_dump_path=flight_dump_path,
                                     name="serving-engine")
        # metrics facade: the process-global registry, optionally
        # wrapped in a label-stamping view (Router-built replicas pass
        # metrics_labels={"replica": "<i>"} so one process's series
        # stay distinguishable and merged_across("replica") can fold
        # them back into the tier export). Storage stays in the global
        # registry either way — counter_total / exporters see one pool.
        # tpu-lint: volatile(telemetry facade; the Router re-stamps it
        # via engine kwargs on restore/rebuild)
        self._metrics = (registry().view(**metrics_labels)
                         if metrics_labels else registry())
        self._step_seq = 0              # flight event ordinal
        # tpu-lint: volatile(flight-dump latch, per tick)
        self._dump_pending: Optional[str] = None
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_admitted: List[int] = []
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_retired: List = []
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_prefills: List = []
        # tpu-lint: volatile(per-tick segment timing)
        self._tick_prefill_s = 0.0
        # overload-control tick markers + capacity estimator state
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_preempted: List[int] = []
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_resumed: List[int] = []
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_swapped_out: List[int] = []
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_swapped_in: List[int] = []
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_shed: List = []      # (request_id, reason) pairs
        # tpu-lint: volatile(shed results land in results, which the
        # snapshot serializes; the tick report is per-incarnation)
        self._pending_finished: List[int] = []  # shed between ticks
        # tpu-lint: volatile(capacity estimator re-learns; cold = no
        # estimate, the documented estimated_ttft_s convention)
        self._ewma_step = _Ewma()       # decode dispatch+sync per step
        # prefill cost PER TOKEN (wall seconds / new tokens prefilled):
        # the estimator must price a 2048-token prompt ~64x a 32-token
        # one, not one flat wave term — deadline-infeasibility shedding
        # would otherwise over-shed short prompts queued behind long
        # ones (tests/test_serving_chunked.py pins the bimodal case)
        # tpu-lint: volatile(capacity estimator re-learns)
        self._ewma_prefill_tok = _Ewma()
        # tpu-lint: volatile(capacity estimator re-learns)
        self._ewma_chunk = _Ewma()      # per chunk-program wall time
        # chunked-prefill scheduler state: FIFO of _ChunkGroup batches
        # still mid-prefill (dead rows lazily compacted by identity
        # check), chunk events this tick, and decode dispatches since
        # the last chunk (the decode_per_chunk interleave budget;
        # initialized satisfied so the first chunk runs immediately)
        # tpu-lint: volatile(mid-prefill slots snapshot as resumable
        # requests; restore re-admits them through the queue)
        self._prefill_fifo: List[_ChunkGroup] = []
        # tpu-lint: volatile(per-tick flight marker)
        self._tick_chunks: List = []    # (request_id, start, ntok)
        # tpu-lint: volatile(interleave budget restarts satisfied)
        self._decode_since_chunk = self.decode_per_chunk
        # tpu-lint: volatile(a restored engine re-pays the compile)
        self._step_fn_warm = False      # first dispatch pays the compile
        # tpu-lint: volatile(a restored engine re-pays the compile)
        # the PLAIN decode program's own first-dispatch guard: in a
        # chunked engine the first dispatch is a fused chunk tick, so
        # _step_fn_warm flips long before the chunkless step program
        # first compiles — gating the _ewma_step feed on _step_fn_warm
        # alone would ingest that compile spike and over-shed
        # deadline-carrying submits for dozens of ticks
        self._ewma_step_warm = False
        # sanitizer tiers (paddle_tpu.analysis.runtime,
        # docs/ANALYSIS.md): "dispatch" (== True, the PR 9 behavior)
        # wraps every STEADY-STATE fused dispatch — warm step program,
        # no join/leave/table event since the last upload — in
        # no_transfer(h2d) + no_recompile, so a stray host upload or
        # shape-churn recompile raises at the offending step;
        # "roundtrip" runs the snapshot->restore->snapshot byte-
        # identity check inside every save_snapshot; "all" arms both.
        if sanitize in (False, None):
            mode = None
        elif sanitize is True or sanitize == "dispatch":
            mode = "dispatch"
        elif sanitize in ("roundtrip", "all"):
            mode = sanitize
        else:
            raise ValueError(
                f"sanitize must be a bool or one of "
                f"'dispatch'/'roundtrip'/'all', got {sanitize!r}")
        self._sanitize = mode in ("dispatch", "all")
        self._sanitize_roundtrip = mode in ("roundtrip", "all")
        # the constructor-shaped value, so snapshots round-trip the
        # configured tier (not the normalized booleans)
        self._sanitize_mode = (sanitize if isinstance(sanitize, str)
                               else bool(sanitize))
        self._gauges_init()

    # ------------------------------------------------------------- helpers
    def _count_layers(self) -> int:
        cfg = self.model.cfg
        return int(getattr(cfg, "num_layers"))

    # -------------------------------------------- tensor-parallel plumbing
    _EMBED_KEYS = ("model.embed_tokens.weight", "gpt.wte.weight")

    def _share_draft_embeddings(self, draft_state):
        """Rebind the draft's embedding table to the TARGET's array when
        shape+dtype match (SpecConfig(share_embeddings=True)). Returns
        the rebound dict, or None when no key lines up — a smaller-
        hidden draft keeps its own table, silently."""
        for key in self._EMBED_KEYS:
            tw = self._state.get(key)
            dw = draft_state.get(key)
            if (tw is not None and dw is not None
                    and getattr(tw, "shape", None) == dw.shape
                    and getattr(tw, "dtype", None) == dw.dtype):
                out = dict(draft_state)
                out[key] = tw
                return out
        return None

    def _up(self, x, spec=None):
        """Host→device upload for program inputs. Single-device engines
        take the plain ``jnp.asarray`` path (byte-identical pre-mp
        behavior); a mesh-sharded engine commits the upload under an
        explicit NamedSharding (replicated unless ``spec`` says
        otherwise) so dispatch inputs never mix mesh and single-device
        placements."""
        if self.layout is None:
            return jnp.asarray(x)
        from jax.sharding import PartitionSpec
        # tpu-lint: allow(host-sync): inputs are host-canonical mirrors
        return self.layout.place(
            np.asarray(x), spec if spec is not None else PartitionSpec())

    def _up_scales(self):
        """The int8 per-slot scale device twin: canonical on the host,
        shard-major permuted + head-dim sharded on the mesh (lockstep
        with the pool's last dim)."""
        if self.layout is None:
            return jnp.asarray(self._kv_scales)
        return self.layout.shard_kv_scales(
            self._kv_scales, num_kv_heads=self.meta["num_kv_heads"],
            head_dim=self.meta["head_dim"])

    def _wrap_program(self, impl, in_specs, out_specs, donate_argnums=()):
        """The ONE shard seam (ISSUE 17): every engine program routes
        through here. mesh=None → plain ``jax.jit`` — the exact pre-mp
        program. With a mesh, the impl runs under full-manual
        ``jax.shard_map``: per-head math is local, the o-proj/logits
        boundary gathers (inside fused_decode), and sampling runs
        replicated on every device so per-slot ``fold_in`` RNG streams
        survive verbatim. check_vma/check_rep=False is REQUIRED: the
        replication checker cannot infer that all_gather outputs under
        replicated out_specs are in fact replicated (jaxcompat
        forwards the flag on 0.4.x)."""
        if self.mesh is None:
            return jax.jit(impl, donate_argnums=donate_argnums)
        try:
            sm = jax.shard_map(impl, mesh=self.mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False,
                               check_rep=False)
        except TypeError:   # newer jax: check_rep renamed to check_vma
            sm = jax.shard_map(impl, mesh=self.mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False)
        return jax.jit(sm, donate_argnums=donate_argnums)

    def _gather_stacked(self, stacked):
        """fsdp gather-at-use: stacked leaves arrive sharded on the
        layer dim; one tiled all_gather per leaf at body entry
        reassembles the exact bytes (bitwise inert). mp-only meshes
        (and mesh=None) pass through untouched."""
        if self._fsdp_axis is None:
            return stacked
        ax = self._fsdp_axis
        return {k: jax.lax.all_gather(w, ax, axis=0, tiled=True)
                for k, w in stacked.items()}

    def _replicated_specs(self, tree):
        """A matching pytree of replicated PartitionSpecs."""
        from jax.sharding import PartitionSpec
        return jax.tree.map(lambda _: PartitionSpec(), tree)

    def _gauges_init(self):
        r = self._metrics
        r.gauge("serving.pool_blocks_total").set(self.pool.num_blocks - 1)
        r.gauge("serving.mp_degree").set(self._mp)
        r.gauge("serving.fsdp_degree").set(
            self.layout.fsdp if self.layout is not None else 1)
        if self.host_store is not None:
            r.gauge("serving.offload.host_blocks_total").set(
                self.host_store.capacity)
        self._update_gauges()

    def _update_gauges(self):
        r = self._metrics
        active = sum(s is not None for s in self._slots)
        r.gauge("serving.batch_occupancy").set(active / self.max_slots)
        r.gauge("serving.queue_depth").set(len(self._queue))
        r.gauge("serving.pool_blocks_used").set(self.pool.used_blocks)
        if self.prefix_cache is not None:
            r.gauge("serving.prefix_hit_rate").set(
                self.prefix_cache.hit_rate)
        if self.host_store is not None:
            r.gauge("serving.offload.host_blocks_used").set(
                self.host_store.used_blocks)
            probes = (self.stats["prefetch_hits"]
                      + self.stats["prefetch_misses"])
            if probes:
                r.gauge("serving.offload.prefetch_hit_rate").set(
                    self.stats["prefetch_hits"] / probes)

    def _fresh_stats(self) -> Dict:
        """The ONE definition of the cumulative stats dict — __init__
        and reset_stats both take it from here, so a new field (the
        step-segment times, admission count) cannot drift between the
        two copies. ``step_*_s`` are cumulative wall seconds per step
        segment; per-step distributions live in the
        ``serving.step_*_s`` registry histograms."""
        return dict(steps=0, decode_tokens=0, idle_slot_steps=0,
                    prefill_tokens=0, prefill_tokens_reused=0,
                    prefill_chunks=0, replay_tokens=0,
                    requests_finished=0, requests_admitted=0,
                    preemptions=0, requests_resumed=0,
                    requests_shed=0, requests_rejected=0,
                    sanitized_steps=0, decode_slot_dispatches=0,
                    spec_ticks=0, spec_proposed=0, spec_accepted=0,
                    spec_k_probes=0, roundtrip_checks=0,
                    swap_outs=0, swap_ins=0,
                    swap_out_bytes=0, swap_in_bytes=0,
                    prefetch_hits=0, prefetch_misses=0,
                    step_admit_s=0.0, step_prefill_s=0.0,
                    step_dispatch_s=0.0, step_sync_s=0.0)

    def reset_stats(self):
        """Zero the cumulative throughput counters and step-segment
        times (and the prefix cache's hit accounting) — bench warmup ->
        measured pass."""
        self.stats = self._fresh_stats()
        if self.prefix_cache is not None:
            self.prefix_cache.hit_blocks = 0
            self.prefix_cache.lookup_blocks = 0

    @property
    def active_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def queued(self) -> int:
        return len(self._queue)

    @property
    def idle(self) -> bool:
        return self.active_slots == 0 and not self._queue

    # ---------------------------------------------------------- submission
    def _count_rejected(self, request: Request, reason: str):
        self._metrics.counter("serving.rejected", reason=reason).inc()
        self.stats["requests_rejected"] += 1
        # tpu-lint: allow(journal-coverage): submit-time rejection —
        # the request was never ACCEPTED, so the zero-loss journal owes
        # it nothing (the router counts tier-level rejects separately)
        self._tick_shed.append((request.request_id, reason))
        # at most one overload dump per tick, at the next step boundary
        # (a per-rejection dump would flood the sink under sustained
        # overload — the ring already carries the lead-up)
        if self._dump_pending is None:
            self._dump_pending = f"rejected:{reason}"

    def _shed_queued(self, victim: Request, reason: str):
        """Drop a queued request (displacement under a full bounded
        queue): it finishes with ``finish='shed'`` — reported, never
        silently lost. A previously-preempted victim keeps the tokens
        it already generated (like a deadline cut), not an empty
        result."""
        self._queue.remove(victim)
        self._drop_parked(victim.request_id)
        toks = victim._resume_tokens or []
        ttft = (victim._t_first - victim._t_submit
                if victim._t_first is not None
                and victim._t_submit is not None else None)
        # tpu-lint: allow(journal-coverage): engine-level displacement;
        # the Router rescues the victim onto a sibling replica or
        # journals "finish" when it collects this shed result —
        # single-engine durability is the snapshot, which serializes
        # results
        res = RequestResult(victim.request_id, victim.prompt, toks,
                            len(toks), "shed", ttft, None, 0,
                            trace_id=victim.trace_id)
        self.results[victim.request_id] = res
        self._pending_finished.append(victim.request_id)
        r = self._metrics
        r.counter("serving.rejected", reason=reason).inc()
        r.counter("serving.requests", finish="shed").inc()
        self.stats["requests_shed"] += 1
        self._tick_shed.append((victim.request_id, reason))
        if self._dump_pending is None:
            self._dump_pending = "shed"

    def estimated_ttft_s(self, request: Request,
                         default: Optional[float] = None
                         ) -> Optional[float]:
        """EWMA-capacity estimate of ``request``'s queue-wait + prefill
        time (the earliest its first token could land): decode work
        ahead of it (active slots' remaining budgets + queued requests
        at >= its priority) spread over ``max_slots`` at the EWMA
        decode step time, plus prefill work priced PER TOKEN — its own
        prompt AND the >=rank prompts queued/prefilling ahead of it, so
        a 2048-token prompt costs ~64x a 32-token one instead of one
        flat wave term (long-prompt bias would over-shed short prompts
        queued behind long ones). On a chunked engine the request's own
        prefill is priced as ceil(prompt/chunk_tokens) full chunks plus
        the ``decode_per_chunk`` decode dispatches interleaved between
        them. Fed by the same segment wall times the
        ``serving.step_*_s`` histograms observe.

        **Cold convention** (the defined contract, not an accident): an
        engine that has not completed one warm decode dispatch has NO
        capacity estimate and returns ``default`` (``None`` unless
        overridden) — never a guess. The two caller conventions:

        * *admission* (``shed_infeasible``) treats cold as
          never-shed — a request must not be rejected on zero
          evidence (``default=None``, the engine's own use);
        * *placement* (the serving :class:`~paddle_tpu.serving.Router`)
          treats cold as maximally available — an idle just-added
          replica should attract load so its estimate warms up
          (``default=0.0``).

        Callers that cannot special-case ``None`` pass the convention
        they want as ``default`` instead of re-implementing it."""
        if self._ewma_step.value is None:
            return default
        step_s = self._ewma_step.value
        tok_s = self._ewma_prefill_tok.value or 0.0
        # only work at >= this request's priority counts as "ahead":
        # strictly lower-priority slots are exactly what admission
        # would preempt for it, and lower-priority queue entries sort
        # behind it — counting either would shed feasible high-priority
        # deadlines
        ahead = sum(s.req.max_new_tokens - s.count
                    for s in self._slots
                    if s is not None and s.req.rank >= request.rank)
        ahead += sum(r.max_new_tokens - len(r._resume_tokens or [])
                     for r in self._queue.items()
                     if r.rank >= request.rank)
        # prefill tokens ahead: queued >=rank feeds (prompt + resume
        # tokens they re-prefill) and the unprefilled remainder of
        # slots still mid-chunk
        ahead_pf = sum(len(r.prompt) + len(r._resume_tokens or [])
                       for r in self._queue.items()
                       if r.rank >= request.rank)
        ahead_pf += sum(len(s.feed) - s.filled
                        for s in self._slots
                        if s is not None and s.prefilling
                        and s.req.rank >= request.rank)
        P = len(request.prompt)
        if self.chunk_tokens is not None:
            # priced at the autotuner's CURRENT bucket (== chunk_tokens
            # until a warm EWMA moves it)
            CT = self._chunk_choice or self.chunk_tokens
            n_chunks = -(-P // CT)
            own = (n_chunks * CT * tok_s
                   + (n_chunks - 1) * self.decode_per_chunk * step_s)
        else:
            own = P * tok_s
        # with speculation on, one dispatch commits an accepted-length
        # EWMA of tokens per slot (>= 1), so the decode work ahead
        # drains that much faster — pricing it at one token per step
        # would over-shed feasible deadlines exactly when speculation
        # is winning (tests/test_serving_spec.py pins the regression)
        tpt = max(self._ewma_spec_tokens.value or 1.0, 1.0)
        return (own + ahead_pf * tok_s
                + (ahead / (self.max_slots * tpt)) * step_s)

    def _check_fits(self, request: Request, count: bool):
        """The structural admissibility checks shared by
        :meth:`submit` and :meth:`admit_resumable` — ``count`` controls
        whether a refusal lands on the ``serving.rejected`` telemetry
        (submit's shed accounting; the force-admit path raises bare)."""
        P = len(request.prompt)
        worst = -(-(P + request.max_new_tokens - 1) // self.block_tokens)
        if worst > self.max_blocks_per_slot:
            if count:
                self._count_rejected(request, "too_long")
            raise ValueError(
                f"request needs {worst} blocks "
                f"({P}+{request.max_new_tokens} tokens) but max_seq_len "
                f"{self.max_seq_len} caps a slot at "
                f"{self.max_blocks_per_slot}")
        # never-fits check: optimistic bound only — with prefix caching
        # up to (P-1)//BT prompt blocks may be shared, so don't reject a
        # request the cache could make admissible. The dtype-accurate
        # reservation (int8 hits share NO physical blocks) lives in
        # _admit, where an over-sized request queues instead of raising.
        lookup = ((P - 1) // self.block_tokens
                  if self.prefix_cache is not None else 0)
        if worst - lookup > self.pool.num_blocks - 1:
            if count:
                self._count_rejected(request, "never_fits")
                self.flight.auto_dump("pool_exhausted:submit")
            raise PoolExhausted(
                f"request needs at least {worst - lookup} blocks; the "
                f"whole pool has {self.pool.num_blocks - 1}")

    def _enqueue(self, request: Request) -> int:
        """Seed assignment + submit stamping + queue push — the one
        admission tail behind :meth:`submit` and
        :meth:`admit_resumable`."""
        if request.seed is None:
            request.seed = self.seed + self._seeds_issued
            self._seeds_issued += 1
        request._t_submit = time.perf_counter()
        request._seq = self._submit_seq
        self._submit_seq += 1
        self._queue.push(request)
        self._update_gauges()
        return request.request_id

    def submit(self, request) -> int:
        """Queue a request (accepts a :class:`Request` or a 1-D prompt).
        Returns the request id; the result lands in ``self.results``.

        May raise: ``ValueError`` (request cannot fit a slot at all),
        :class:`PoolExhausted` (needs more blocks than the whole pool),
        :class:`Rejected` (load shedding — bounded queue full with no
        lower-priority victim, or deadline infeasible under the current
        capacity estimate). Every shed path is counted under
        ``serving.rejected{reason}``."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        if not isinstance(request, Request):
            request = Request(request)
        self._check_fits(request, count=True)
        if self.shed_infeasible and request.deadline_s is not None:
            est = self.estimated_ttft_s(request)
            if est is not None and est > request.deadline_s:
                self._count_rejected(request, "deadline_infeasible")
                raise Rejected(
                    "deadline_infeasible",
                    f"request {request.request_id} deadline "
                    f"{request.deadline_s:.3f}s < estimated "
                    f"queue-wait+prefill {est:.3f}s — it would expire "
                    f"before its first token")
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            victim = self._queue.lowest_below(request.rank)
            if victim is None:
                self._count_rejected(request, "queue_full")
                raise Rejected(
                    "queue_full",
                    f"queue at capacity ({self.max_queue}) with no "
                    f"lower-priority request to displace")
            self._shed_queued(victim, "displaced")
        return self._enqueue(request)

    def admit_resumable(self, request,
                        tokens: Optional[Sequence[int]] = None) -> int:
        """Force-admit a request BYPASSING the overload controls
        (bounded queue, displacement, deadline-infeasibility shedding)
        — the re-admission primitive behind :meth:`restore` and the
        router's failover / drain migration. A request on this path was
        already *accepted* once; shedding it now would turn a recovery
        action into data loss, exactly what the zero-loss contract
        forbids. ``tokens`` (generated so far) arms the token-exact
        resume: the engine re-prefills the prompt, replays the tokens
        through the decode step program and continues the request's
        own ``fold_in(seed, count)`` stream, so the final tokens are
        bit-identical to an uninterrupted run. The
        *structural* checks still apply — a request that cannot fit a
        slot (``ValueError``) or the whole pool (``PoolExhausted``)
        raises exactly like :meth:`submit`; config-identical replicas
        would have rejected it at the original accept too."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        if not isinstance(request, Request):
            request = Request(request)
        self._check_fits(request, count=False)
        if tokens is not None:
            request._resume_tokens = list(tokens) or None
        return self._enqueue(request)

    def release_request(self, request_id: int) -> Optional[List[int]]:
        """Remove one UNFINISHED request from this engine entirely and
        return its generated-so-far tokens (the token-exact resume
        state another engine re-admits through
        :meth:`admit_resumable`) — the role-migration primitive: a
        prefill-role replica releases a request at first token and the
        router re-places it on a decode-role replica. An active slot
        goes through the preemption path first (blocks freed, full
        bf16 blocks donated to the prefix cache, resume tokens
        captured), then the requeued request is popped back out.
        Returns None when this engine does not hold the request
        unfinished (already retired, or never here) — the caller must
        NOT re-place it elsewhere in that case."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        rid = int(request_id)
        slot_idx = next((i for i, s in enumerate(self._slots)
                         if s is not None and s.req.request_id == rid),
                        None)
        if slot_idx is not None:
            self._preempt(slot_idx)
        for req in list(self._queue.items()):
            if req.request_id == rid:
                self._queue.remove(req)
                # the KV is leaving this engine — any host-tier parked
                # copy (including one the _preempt above just made) is
                # dead weight here; the migration target re-prefills or
                # receives the blocks through the tier prefix store
                self._drop_parked(rid)
                self._update_gauges()
                return list(req._resume_tokens or [])
        return None

    def inflight_tokens(self) -> Dict[int, List[int]]:
        """``{request_id: generated-so-far tokens}`` for every
        UNFINISHED request this engine holds — active decode slots, mid
        prefill slots (which report the resume tokens they were
        admitted with) and queued requests (their resume tokens, empty
        for fresh ones). The router's per-tick progress mirror: by the
        resume contract, re-placing a dead replica's request with any
        *prefix* of its true token stream (whatever this method last
        reported) stays token-exact."""
        out: Dict[int, List[int]] = {}
        for s in self._slots:
            if s is None:
                continue
            out[s.req.request_id] = (list(s.resume or []) if s.prefilling
                                     else list(s.tokens))
        for r in self._queue.items():
            out[r.request_id] = list(r._resume_tokens or [])
        return out

    # ----------------------------------- tier-wide prefix store surface
    def export_prefix_blocks(self, keys: Sequence[str]
                             ) -> Dict[str, Tuple[int, np.ndarray]]:
        """Exact bf16 KV payloads for the requested prefix-chain keys
        (hex) this replica's cache still holds — the tier-wide prefix
        store's fetch path. bf16 pools gather the physical blocks out
        of the pool in ONE bucketed dispatch; int8 pools return the
        cache's exact bf16 host copies. Missing keys are silently
        absent: the tier index is a hint, and a partial fetch just
        shortens the copied run."""
        out: Dict[str, Tuple[int, np.ndarray]] = {}
        if self.prefix_cache is None or self._closed:
            return out
        want = []
        for k in keys:
            e = self.prefix_cache.entry(k)
            if e is None:
                continue
            if e.kv_host is not None:
                # tpu-lint: allow(host-sync): kv_host is a host copy
                out[k] = (e.depth, np.asarray(e.kv_host))
            elif e.block_id is not None:
                want.append((k, e))
        if want:
            m = _swap_bucket(len(want))
            bids = np.full(m, SCRATCH_BLOCK, np.int32)
            bids[:len(want)] = [e.block_id for _, e in want]
            buf = self._swap_fn("gather")(self.kv_pool, self._up(bids))
            # tpu-lint: allow(host-sync): once-per-fetch D2H — prefix
            # blocks ship across the tier as host arrays
            buf = np.asarray(buf)
            for c, (k, e) in enumerate(want):
                # tpu-lint: allow(host-sync): host slice copy
                out[k] = (e.depth, np.ascontiguousarray(buf[:, c]))
        return out

    def import_prefix_blocks(self, entries: Dict[str, Tuple]) -> int:
        """Adopt another replica's prefix blocks into THIS replica's
        cache — the tier-wide prefix store's delivery path. bf16 pools
        allocate physical blocks and scatter the payloads in (one
        bucketed dispatch; the cache owns the refs, so a later
        admission shares them exactly like locally prefilled blocks);
        int8 pools keep the exact bf16 host copies and requantize at
        adoption — the cache's native int8 representation. Entries
        already cached, or that the pool has no spare room for, are
        skipped (a miss, not an error). Returns blocks added."""
        cache = self.prefix_cache
        if cache is None or self._closed or not entries:
            return 0
        added = 0
        todo = []
        for k, (depth, kv) in entries.items():
            if self.kv_int8:
                # tpu-lint: allow(host-sync): wire payloads are host
                if cache.adopt_entry(k, depth,
                                     kv_host=np.asarray(kv)):
                    added += 1
            elif cache.entry(k) is None:
                todo.append((k, int(depth), kv))
        if todo:
            # never squeeze live work: only free-and-unreserved blocks
            # (plus idle cache blocks) host imported prefixes
            free = self.pool.free_blocks - self._reserved
            if len(todo) > free:
                cache.evict_free(len(todo) - free)
                free = self.pool.free_blocks - self._reserved
            todo = todo[:max(free, 0)]
        if todo:
            bids = self.pool.alloc(len(todo))
            m = _swap_bucket(len(todo))
            dbids = np.full(m, SCRATCH_BLOCK, np.int32)
            dbids[:len(todo)] = bids
            buf = np.zeros((self._num_layers, m, self.block_tokens,
                            2 * self._dkv), jnp.dtype(self.cache_dtype))
            for c, (_, _, kv) in enumerate(todo):
                buf[:, c] = kv
            dev = (self.layout.place(buf, self.layout.pool_spec())
                   if self.layout is not None else jax.device_put(buf))
            self.kv_pool = self._swap_fn("scatter")(
                self.kv_pool, self._up(dbids), dev)
            for bid, (k, depth, _) in zip(bids, todo):
                if cache.adopt_entry(k, depth, block_id=bid):
                    added += 1
                else:       # raced into the cache meanwhile: give back
                    self.pool.free(bid)
        if added:
            self._metrics.counter(
                "serving.offload.prefix_import_blocks").inc(added)
        return added

    # ------------------------------------------------------------- prefill
    def _prefill_wave_fn(self, R, s_pad, n):
        """Batched prefill program for a WAVE of ``n`` same-shape
        admissions (shared prefix depth ``R``, padded prompt tail
        ``s_pad``): the prefix gather (bf16: straight from the pool),
        the forward pass, the pool adopt scatter, the int8 calibration,
        and the first-token sample are ONE dispatch. A b=1 prefill of a
        short prompt streams every weight once — the same traffic as a
        whole decode step — so admissions that land on the same tick
        share one weight pass and one pool write instead of paying both
        per request.

        Returns ``(fn, cached)`` — ``cached=False`` means this call
        will pay the trace+compile, which the EWMA capacity estimator
        must not ingest (a multi-second compile spike would make
        ``shed_infeasible`` reject feasible deadlines for dozens of
        steps; the ``serving.step_prefill_s`` histogram still sees it).
        """
        from paddle_tpu.inference import (_fold_rows, _row_keys,
                                          _sample_logits)
        from paddle_tpu.nn.layer import functional_call

        key = ("prefill", self.kv_int8, R, s_pad, n)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn, True
        nkv, hd = self.meta["num_kv_heads"], self.meta["head_dim"]
        dkv = self._dkv
        BT = self.block_tokens
        cache_len = R + s_pad
        hb = R // BT                 # shared prefix blocks per row
        nb_new = s_pad // BT         # freshly prefilled blocks per row
        n0 = hb + nb_new             # blocks covering the whole prompt
        model = self.model
        int8 = self.kv_int8
        mp_axis = self._mp_axis

        def impl(state, pool, prefix, ids, last_idx, seeds, new_bids,
                 valid_len):
            # prefix: bf16 pools pass the (n, hb) shared block ids and
            # gather the prefix KV HERE (no separate dispatch); int8
            # pools pass the host-kept bf16 copies (L, n, R, 2dkv) —
            # quantized blocks are per-slot-scaled, never shareable.
            # Under mp the pool's last dim is the LOCAL [k_s|v_s] lanes
            # (pool.shape[-1] == 2dkv/mp inside the shard): prefix
            # gathers reassemble the canonical width, adopt scatters
            # keep only the shard's own lanes.
            cache = model.init_cache(n, cache_len, dtype=jnp.bfloat16)
            if R:
                if int8:
                    pk = prefix
                else:
                    pk = pool[:, prefix].reshape(
                        len(cache), n, R, pool.shape[-1])
                    if mp_axis is not None:
                        pk = mp_gather_kv_lastdim(pk, mp_axis)
                for l in range(len(cache)):
                    kl = pk[l, :, :, :dkv].reshape(n, R, nkv, hd)
                    vl = pk[l, :, :, dkv:].reshape(n, R, nkv, hd)
                    cache[l] = {
                        "k": cache[l]["k"].at[:, :R].set(
                            kl.astype(cache[l]["k"].dtype)),
                        "v": cache[l]["v"].at[:, :R].set(
                            vl.astype(cache[l]["v"].dtype))}
            with jax.named_scope("decode.prefill"):
                out, cache = functional_call(model, state, ids,
                                             cache=cache, start_pos=R)
            kv_flat = jnp.stack([jnp.concatenate(
                [c["k"].reshape(n, cache_len, dkv),
                 c["v"].reshape(n, cache_len, dkv)], axis=-1)
                for c in cache])                 # (L, n, cache_len, 2dkv)
            logits = jnp.take_along_axis(
                out, last_idx[:, None, None], axis=1)[:, 0]   # (n, vocab)
            keys = _row_keys(seeds)
            with jax.named_scope("decode.sample"):
                tok = _sample_logits(logits, _fold_rows(keys, 0),
                                     self.temperature, self.top_k,
                                     self.top_p)
            if int8:
                # per-request calibration: amax over each row's VALID
                # prompt positions only — the padded tail holds
                # pad-token kv, which must not leak into the scales
                # (matches quantize_kv_cache over a contiguous cache)
                mask = (jnp.arange(cache_len)[None]
                        < valid_len[:, None])[None, :, :, None]
                a = jnp.where(mask, jnp.abs(kv_flat.astype(jnp.float32)),
                              0.0).max(axis=2)              # (L, n, 2dkv)
                a = a.reshape(-1, n, 2 * nkv, hd).max(axis=-1)
                lanes = jnp.repeat(jnp.maximum(a / 127.0, 1e-8), hd,
                                   axis=-1)                 # (L, n, 2dkv)
                q = jnp.clip(jnp.round(
                    kv_flat.astype(jnp.float32) / lanes[:, :, None, :]),
                    -127, 127).astype(jnp.int8)
                blkq = q.reshape(-1, n, n0, BT, 2 * dkv)
                if mp_axis is not None:
                    blkq = mp_local_kv_lastdim(blkq, mp_axis)
                pool = pool.at[:, new_bids].set(blkq)
                return tok, pool, lanes, kv_flat
            blk = kv_flat[:, :, R:cache_len].reshape(
                -1, n, nb_new, BT, 2 * dkv)
            if mp_axis is not None:
                blk = mp_local_kv_lastdim(blk, mp_axis)
            pool = pool.at[:, new_bids].set(blk.astype(pool.dtype))
            return tok, pool

        # `state` flows as a traced argument (matching generate) so the
        # weights are not baked into the program as constants
        from jax.sharding import PartitionSpec as P
        lay = self.layout
        pspec = lay.pool_spec() if lay is not None else None
        in_specs = (P(), pspec) + (P(),) * 6
        out_specs = ((P(), pspec, P(), P()) if int8 else (P(), pspec))
        jitted = self._wrap_program(impl, in_specs, out_specs,
                                    donate_argnums=(1,))
        fn = _program_handle(jitted, lambda: (self._state,))
        self._jit_cache[key] = fn
        return fn, False

    def _autotune_chunk(self, s_pad: int) -> int:
        """The chunk size for a freshly admitted prefill: with
        ``chunk_autotune`` off, the configured ``chunk_tokens``; with
        it on, the LARGEST bucket on the power-of-two ladder anchored
        at ``chunk_tokens`` whose predicted fused-tick time fits under
        the ``slo_tpot_s`` headroom — on a fused engine a chunk tick IS
        a decode latency for every active slot, so the chunk budget is
        the TPOT SLO minus nothing (the decode half rides inside the
        same program). Predictions use the per-bucket tick-time EWMA
        where one exists, else the per-token prefill EWMA times the
        bucket (plus the decode-step EWMA the fused tick carries).
        Re-evaluated at bucket boundaries only — once per admission
        group, never mid-prefill — so the cursor lattice (and with it
        the compile set) stays finite and pinnable: a bucket transition
        compiles exactly its new (start, chunk, C_pad) programs and
        nothing twice (tests/test_analysis.py). Returns the
        PER-ADMISSION pick — clamped at the first bucket covering
        ``s_pad``, possibly probe-overridden; the un-clamped SLO pick
        is what persists in ``_chunk_choice`` for
        :meth:`estimated_ttft_s` pricing (a short admission's clamp,
        or a probe's unmeasured bucket, must not re-price every other
        queued prompt)."""
        base = self.chunk_tokens
        if not self.chunk_autotune:
            return base
        tok = self._ewma_prefill_tok.value
        if tok is None:
            pick = pricing = base   # cold: no evidence, no tuning
        else:
            step = self._ewma_step.value or 0.0

            def largest_fit(cs):
                best = None
                for c in cs:        # ascending: keep the largest
                    ew = self._chunk_time_ewma.get(c)
                    pred = (ew.value if ew is not None
                            and ew.value is not None
                            else tok * c + step)
                    if pred <= self.slo_tpot_s:
                        best = c
                return cs[0] if best is None else best

            cands = [base]
            c = base // 2           # ladder: power-of-two multiples of
            while c >= self.block_tokens and c % self.block_tokens == 0:
                cands.insert(0, c)  # the configured anchor, down to
                c //= 2             # one block and up to the slot cap
            c = base * 2
            while c <= self.max_seq_len:
                cands.append(c)
                c *= 2
            # the PRICING pick is evaluated on the FULL ladder — it is
            # what estimated_ttft_s charges every queued prompt, so the
            # per-admission clamp/probe below must not leak into it (a
            # 16-token admission's clamped bucket would over-price a
            # long deadline-carrying submit severalfold and over-shed)
            pricing = largest_fit(cands)
            # clamp at the FIRST bucket covering this admission's feed
            # bucket, in both directions — a chunk wider than s_pad is
            # pure padding (it forwards, and compiles programs for,
            # positions the prompt doesn't have), including when the
            # covering bucket sits below the configured anchor
            cover = next((i for i, cc in enumerate(cands)
                          if cc >= s_pad), len(cands) - 1)
            del cands[cover + 1:]
            pick = largest_fit(cands)
            # one-step-up probing (the spec k=0 recovery-probe
            # pattern): the linear per-token prediction is badly
            # pessimistic on weight-stream-dominated backends — a 4x
            # chunk costs nowhere near 4x a tick — so an UNMEASURED
            # next bucket would never be chosen on prediction alone
            # and its per-bucket EWMA could never observe. Every
            # _CHUNK_PROBE_EVERY tuned admissions, pick the next
            # bucket up ONCE so it gets measured; evidence (not the
            # prediction) then decides whether the pick climbs.
            # the wait counter advances ONLY on probe-eligible
            # admissions and is frozen (not reset) by ineligible ones
            # — a short prompt whose clamped ladder tops out at the
            # current pick must not starve the long prompts' probe
            # under an interleaved length mix
            nxt = next((c for c in cands if c > pick), None)
            if (nxt is not None and nxt not in self._chunk_time_ewma
                    and self._chunk_probe_tries.get(nxt, 0)
                    < _CHUNK_PROBE_TRIES):
                self._chunk_probe_wait += 1
                if self._chunk_probe_wait >= _CHUNK_PROBE_EVERY:
                    self._chunk_probe_wait = 0
                    self._chunk_probe_tries[nxt] = (
                        self._chunk_probe_tries.get(nxt, 0) + 1)
                    pick = nxt
        self._chunk_choice = pricing
        self._metrics.gauge("serving.chunk_autotune").set(pricing)
        return pick

    def _make_chunk_groups(self, wave):
        """Group this tick's chunked admissions by prefill bucket
        ``(R, s_pad)`` and push one :class:`_ChunkGroup` per bucket —
        n same-shape rows advance one chunk each per fused tick (the
        wave batching the n=1 chunk FIFO lost). Every group input is
        uploaded to the device HERE, once per admission (the tick is a
        join event anyway), so subsequent mid-prefill fused ticks
        re-dispatch with zero H2D."""
        BT = self.block_tokens
        L = self._num_layers
        buckets: Dict = {}
        for slot_idx, slot, hits, R, s_pad in wave:
            buckets.setdefault((R, s_pad), []).append((slot_idx, slot))
        for (R, s_pad), rows in buckets.items():
            CT = self._autotune_chunk(s_pad)
            C_pad = R + -(-s_pad // CT) * CT
            g = _ChunkGroup(rows, R, CT, C_pad, self.kv_int8)
            n = len(rows)
            NB = C_pad // BT
            ids = np.zeros((n, C_pad), np.int32)
            bids = np.full((n, NB), SCRATCH_BLOCK, np.int32)
            last_idx = np.zeros(n, np.int32)
            seeds = np.zeros(n, np.uint32)
            valid = np.zeros(n, np.int32)
            last_start = C_pad - CT
            for r, (slot_idx, s) in enumerate(rows):
                P = len(s.feed)
                ids[r, :P] = s.feed
                bids[r, :s.ntab] = s.blocks
                last_idx[r] = P - 1 - last_start
                seeds[r] = np.uint32(s.req.seed)
                valid[r] = len(s.req.prompt)
            g.dev_ids = self._up(ids)
            g.dev_bids = self._up(bids)
            g.dev_last = self._up(last_idx)
            g.dev_seeds = self._up(seeds)
            if self.kv_int8:
                g.dev_valid = self._up(valid)
                if R:
                    # int8 chunk 0 over prefix hits rides the cache's
                    # exact bf16 host copies (quantized blocks are
                    # per-slot-scaled, never shareable) — uploaded once
                    hit_rows = [s.hits for _, s in rows]
                    g.dev_prefix = self._up(np.stack(
                        [np.concatenate([e.kv_host for e in hs], axis=1)
                         for hs in hit_rows], axis=1))   # (L, n, R, 2dkv)
                    assert g.dev_prefix.shape == (L, n, R, 2 * self._dkv)
            for _, s in rows:
                s.hits = None       # consumed; drop the cache refs
            self._prefill_fifo.append(g)
        if buckets:
            self._dirty = True      # join event: mirrors re-upload

    def _compact_group(self, g: "_ChunkGroup"):
        """Drop rows whose slot retired/preempted/unwound mid-prefill
        (identity check — the index may since hold a different slot)
        and slice the group's device inputs (and resident carry) down
        to the survivors. A shrink is an EVENT tick: the n in the
        program key changes, so the next chunk recompiles — preemption
        and deadline sweeps are rare paths, never the steady state."""
        keep = [r for r, (i, s) in enumerate(g.rows)
                if self._slots[i] is s and s.prefilling]
        if len(keep) == len(g.rows):
            return
        g.rows = [g.rows[r] for r in keep]
        if not g.rows:
            return
        # tpu-lint: allow(host-sync): host row-index list, not a device
        # value — the gather below runs on device
        sel = np.asarray(keep, np.int32)
        g.dev_ids = g.dev_ids[sel]
        g.dev_bids = g.dev_bids[sel]
        g.dev_last = g.dev_last[sel]
        g.dev_seeds = g.dev_seeds[sel]
        if g.dev_valid is not None:
            g.dev_valid = g.dev_valid[sel]
        if g.dev_prefix is not None:
            g.dev_prefix = g.dev_prefix[:, sel]
        if g.carry is not None:
            g.carry = g.carry[:, sel]
        self._dirty = True

    def _front_prefill(self) -> Optional["_ChunkGroup"]:
        """The group at the head of the prefill FIFO (compacted to its
        live rows), or None."""
        while self._prefill_fifo:
            g = self._prefill_fifo[0]
            self._compact_group(g)
            if g.rows:
                return g
            self._prefill_fifo.pop(0)
        return None

    def _chunk_body(self, kind, start, n, C_pad, CT, R):
        """Trace-time CHUNK half of the fused tick: forward ``CT``
        prompt tokens for ``n`` same-bucket rows over the KV of the
        ``start`` tokens already processed, advance the RESIDENT carry
        in place, and hand the block-aligned pool payload to the decode
        half (ONE combined scatter inside the same program —
        ``ops.fused_decode.paged_chunk_scatter``).

        ``kind='mid'``: bf16 pools GATHER the processed prefix
        [0, start) straight from pool blocks (every completed chunk
        already scattered; no carry buffer exists at all — the
        O(prompt²/chunk) staging round trip BENCH_r06 caveated is
        simply gone); int8 pools thread the resident bf16 carry
        (L, n, C_pad, 2dkv), RMW'd via a static
        ``dynamic_update_slice`` — the caller donates it, so the
        buffer aliases in place (donation_report pins it). Chunk 0 of
        a multi-chunk int8 prefill CREATES the carry in-program
        (zeros + prefix + chunk — no eager zeros program, no upload).
        ``kind='last'``: samples each row's first token; int8 pools
        calibrate per-slot scales over the ORIGINAL prompt positions
        and quantize+scatter every prompt block in one go (the scale
        deferral that keeps chunked int8 bit-identical to monolithic).

        Returns ``(chunk_bids, chunk_kv, carry2, tok, lanes, kvfull)``
        — any of which may be None depending on kind/dtype."""
        from paddle_tpu.inference import (_fold_rows, _row_keys,
                                          _sample_logits)
        from paddle_tpu.nn.layer import functional_call

        nkv, hd = self.meta["num_kv_heads"], self.meta["head_dim"]
        dkv = self._dkv
        BT = self.block_tokens
        cache_len = start + CT
        model = self.model
        int8 = self.kv_int8
        last = kind == "last"
        keep_kv = self.prefix_cache is not None
        temperature, top_k, top_p = (self.temperature, self.top_k,
                                     self.top_p)
        mp_axis = self._mp_axis

        def body(state, pool, carry, ids, bids, prefix, last_idx,
                 cseeds, valid):
            cache = model.init_cache(n, cache_len, dtype=jnp.bfloat16)
            pk = None
            if start:
                if not int8:
                    # bf16: every completed chunk already scattered its
                    # blocks into the pool, so the processed prefix
                    # GATHERS straight from pool blocks — no carry
                    # buffer at all (the chunk-0 CoW gather generalized
                    # to every cursor; bit-exact, the pool stores the
                    # same bf16 the carry would). Only int8 pools need
                    # the resident bf16 carry (quantized blocks cannot
                    # re-feed the forward).
                    # under mp the pool gather yields the LOCAL lanes;
                    # one tiled all_gather reassembles the canonical
                    # width (the exact bf16 bytes every shard scattered)
                    pk = pool[:, bids[:, :start // BT]].reshape(
                        len(cache), n, start, pool.shape[-1])
                    if mp_axis is not None:
                        pk = mp_gather_kv_lastdim(pk, mp_axis)
                elif start == R:    # int8 chunk 0 over a prefix hit
                    pk = prefix
                else:               # int8 mid/last: the resident carry
                    pk = jax.lax.slice_in_dim(carry, 0, start, axis=2)
                for l in range(len(cache)):
                    kl = pk[l, :, :, :dkv].reshape(n, start, nkv, hd)
                    vl = pk[l, :, :, dkv:].reshape(n, start, nkv, hd)
                    cache[l] = {
                        "k": cache[l]["k"].at[:, :start].set(
                            kl.astype(cache[l]["k"].dtype)),
                        "v": cache[l]["v"].at[:, :start].set(
                            vl.astype(cache[l]["v"].dtype))}
            with jax.named_scope("decode.prefill"):
                out, cache = functional_call(
                    model, state, jax.lax.slice_in_dim(
                        ids, start, cache_len, axis=1),
                    cache=cache, start_pos=start)
            kv_flat = jnp.stack([jnp.concatenate(
                [c["k"].reshape(n, cache_len, dkv),
                 c["v"].reshape(n, cache_len, dkv)], axis=-1)
                for c in cache])             # (L, n, cache_len, 2dkv)
            tok = lanes = kvfull = carry2 = None
            chunk_bids = chunk_kv = None
            if last:
                logits = jnp.take_along_axis(
                    out, last_idx[:, None, None], axis=1)[:, 0]
                with jax.named_scope("decode.sample"):
                    tok = _sample_logits(logits,
                                         _fold_rows(_row_keys(cseeds), 0),
                                         temperature, top_k, top_p)
            if int8 and last:
                # calibration over the original prompt positions only
                # (resume appends beyond the prompt were quantized with
                # prompt-only scales in the uninterrupted run too);
                # padded-tail kv must not leak into the scales either
                mask = (jnp.arange(cache_len)[None]
                        < valid[:, None])[None, :, :, None]
                a = jnp.where(mask, jnp.abs(kv_flat.astype(jnp.float32)),
                              0.0).max(axis=2)          # (L, n, 2dkv)
                a = a.reshape(-1, n, 2 * nkv, hd).max(axis=-1)
                lanes = jnp.repeat(jnp.maximum(a / 127.0, 1e-8), hd,
                                   axis=-1)
                q = jnp.clip(jnp.round(
                    kv_flat.astype(jnp.float32) / lanes[:, :, None, :]),
                    -127, 127).astype(jnp.int8)
                # bids covers every C_pad//BT block; entries past the
                # feed's last allocated block are SCRATCH, so padded-
                # tail garbage lands in the masked scratch block
                chunk_bids = bids
                chunk_kv = q.reshape(-1, n, cache_len // BT, BT, 2 * dkv)
                if keep_kv:
                    kvfull = kv_flat    # host bf16 prefix-cache copies
            elif not int8:
                # bf16: this chunk's blocks scatter as they complete
                chunk_bids = jax.lax.slice_in_dim(
                    bids, start // BT, cache_len // BT, axis=1)
                chunk_kv = kv_flat[:, :, start:].reshape(
                    -1, n, CT // BT, BT, 2 * dkv)
            if int8 and not last:
                new_kv = kv_flat[:, :, start:].astype(jnp.bfloat16)
                if start == R:      # first chunk builds the carry
                    carry2 = jnp.zeros((len(cache), n, C_pad, 2 * dkv),
                                       jnp.bfloat16)
                    if R:
                        carry2 = carry2.at[:, :, :R].set(
                            pk.astype(jnp.bfloat16))
                    carry2 = carry2.at[:, :, R:cache_len].set(new_kv)
                else:               # RMW in place: donated + aliased
                    carry2 = jax.lax.dynamic_update_slice_in_dim(
                        carry, new_kv, start, axis=2)
            return chunk_bids, chunk_kv, carry2, tok, lanes, kvfull

        return body

    def _tick_fn(self, kind, start, n, C_pad, CT, R, K):
        """ONE program per tick: the fused Sarathi coscheduled tick —
        the front group's next prefill chunk AND every decode-ready
        slot's next token (K=0) or k-token verify tail (K>0) dispatch
        together, the pool and resident carry donated and aliased
        in-place. Keyed by the chunk bucket (kind, start, n, C_pad,
        CT, R) × the decode tail K, so the compile set is one program
        per chunk bucket — exactly as pinnable as the two-program
        tick's chunk set was (tests/test_analysis.py).

        Returns ``(fn, cached)`` — ``cached=False`` means this call
        pays the trace+compile, which the EWMA estimators must not
        ingest."""
        from paddle_tpu.inference import resident_carry_donate_argnums

        key = ("tick", kind, self.kv_int8, start, n, C_pad, CT, R, K)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn, True
        chunk_body = self._chunk_body(kind, start, n, C_pad, CT, R)
        spec = K > 0
        ngram = spec and self.speculate.proposer == "ngram"
        dec_body = self._verify_body(K) if spec else self._decode_body()
        int8 = self.kv_int8
        last = kind == "last"
        # only int8 pools thread the resident bf16 carry — bf16 mid
        # chunks gather their processed prefix from the pool itself
        has_carry = int8 and start > R
        has_prefix = int8 and R > 0 and start == R
        keep_kv = self.prefix_cache is not None

        def impl(state, stacked, pool, *rest):
            rest = list(rest)
            carry = rest.pop(0) if has_carry else None
            ids = rest.pop(0)
            bids = rest.pop(0)
            prefix = rest.pop(0) if has_prefix else None
            last_idx = rest.pop(0) if last else None
            cseeds = rest.pop(0) if last else None
            valid = rest.pop(0) if (last and int8) else None
            (tables, positions, toks, seeds, counts,
             kv_scales) = rest[:6]
            srest = rest[6:]
            chunk_bids, chunk_kv, carry2, ctok, lanes, kvfull = \
                chunk_body(state, pool, carry, ids, bids, prefix,
                           last_idx, cseeds, valid)
            if spec:
                proposals, nprop, cap = srest[0], srest[1], srest[2]
                hist = srest[3] if ngram else None
                dec = dec_body(state, stacked, pool, tables, positions,
                               toks, seeds, counts, kv_scales,
                               proposals, nprop, cap, hist,
                               chunk_bids, chunk_kv)
            else:
                dec = dec_body(state, stacked, pool, tables, positions,
                               toks, seeds, counts, kv_scales,
                               chunk_bids, chunk_kv)
            outs = tuple(o for o in (carry2, ctok, lanes,
                                     kvfull if keep_kv else None)
                         if o is not None)
            return (*dec, *outs)

        donate = [2]                # the pool, as every decode program
        if has_carry and not last:
            # the resident carry: RMW'd in place on MID chunks (input
            # shape == output shape — the donation_report pin). A LAST
            # chunk consumes the carry with no matching output, so
            # donating it is declared-but-unusable (jax warns per
            # program and frees the buffer mid-execution on some
            # backends) — the buffer dies with the group right after
            # the tick anyway
            donate.append(3)
        if ngram:
            # the carried ngram history (the _build_verify_fn donation,
            # at its shifted position behind the chunk args)
            donate.append(3 + int(has_carry) + 2 + int(has_prefix)
                          + (2 if last else 0)
                          + (1 if (last and int8) else 0) + 6 + 3)
        from jax.sharding import PartitionSpec as P
        lay = self.layout
        pspec = lay.pool_spec() if lay is not None else None
        sspec = lay.kv_scales_spec() if lay is not None else None
        rest_specs = []
        if has_carry:
            rest_specs.append(P())
        rest_specs += [P(), P()]                    # ids, bids
        if has_prefix:
            rest_specs.append(P())
        if last:
            rest_specs += [P(), P()]                # last_idx, cseeds
        if last and int8:
            rest_specs.append(P())                  # valid
        rest_specs += [P()] * 5 + [sspec]           # tables..kv_scales
        if spec:
            rest_specs += [P(), P(), P()]           # props, nprop, cap
        if ngram:
            rest_specs.append(P())                  # history
        in_specs = (P(), self._stacked_specs or P(), pspec, *rest_specs)
        if spec:
            dec_specs = [P()] * (9 if ngram else 6)
            dec_specs[2] = pspec
        else:
            dec_specs = [P(), pspec, P(), P()]
        n_outs = ((1 if (int8 and not last) else 0)
                  + (1 if last else 0)
                  + ((1 + (1 if keep_kv else 0))
                     if (int8 and last) else 0))
        out_specs = (*dec_specs, *([P()] * n_outs))
        jitted = self._wrap_program(
            impl, in_specs, out_specs,
            donate_argnums=resident_carry_donate_argnums(*donate))
        fn = _program_handle(jitted,
                             lambda: (self._state, self._stacked))
        self._jit_cache[key] = fn
        return fn, False

    def _commit_chunk(self, g: "_ChunkGroup", start, kind, ctok_np,
                      lanes_np, kvfull_np, t_wall, warm):
        """Host-side tail of a fused tick's chunk half: advance every
        row's cursor (mid) or adopt it into the decode batch (last —
        :meth:`_adopt_slot`, the one join path), then the chunk
        telemetry: ``serving.prefill_chunks`` / chunk-size and
        chunk-rows histograms / the prefill-chunk span, the
        warm-tick EWMA feeds (global + per-bucket for the autotuner,
        per COMPUTED token for the estimator), and the chunk-stall
        auto-dump trigger."""
        from paddle_tpu import observability as obs

        CT = g.chunk
        n = g.n
        last = kind == "last"
        for r, (slot_idx, s) in enumerate(g.rows):
            ntok = min(CT, len(s.feed) - start)
            self._tick_chunks.append((s.req.request_id, start, ntok))
            self._metrics.histogram(
                "serving.chunk_tokens",
                buckets=_CHUNK_SIZE_BUCKETS).observe(ntok)
            s.filled = start + CT
            if last:
                self._adopt_slot(
                    slot_idx, s, int(ctok_np[r]),
                    None if lanes_np is None else lanes_np[:, r],
                    None if kvfull_np is None else kvfull_np[:, r])
        if not last and g.dev_prefix is not None and start == g.R:
            # the int8 prefix-hit bf16 copy is consumed by chunk 0
            # only (args() appends it at the R cursor alone) — drop it
            # now rather than hold an (L, n, R, 2dkv) buffer alongside
            # the carry for the rest of a long prefill
            g.dev_prefix = None
        self.stats["prefill_chunks"] += 1
        r = self._metrics
        r.counter("serving.prefill_chunks").inc()
        r.histogram("serving.chunk_rows",
                    buckets=_CHUNK_ROWS_BUCKETS).observe(n)
        tr = obs.active_tracer()
        if tr is not None and g.rows:
            s0 = g.rows[0][1]
            tr.record("serving.prefill_chunk", ts=time.time() - t_wall,
                      dur_s=t_wall, request_id=s0.req.request_id,
                      trace_id=s0.req.trace_id,
                      start=int(start),
                      tokens=int(min(CT, len(s0.feed) - start)),
                      rows=int(n), last=bool(last))
        if warm:    # compile spikes must not poison estimator/stall EWMAs
            ew = self._ewma_chunk.value
            if ew is not None and t_wall > 4.0 * ew \
                    and self._dump_pending is None:
                # a warm fused tick overrunning 4x its EWMA is the
                # chunked-prefill analog of a step_prefill_s outlier —
                # snapshot the ring for the postmortem
                self._dump_pending = "chunk_stall"
            self._ewma_chunk.update(t_wall)
            self._chunk_time_ewma.setdefault(CT, _Ewma()).update(t_wall)
            # per COMPUTED token, not per valid token: the program
            # always forwards the full CT-wide chunk (tails are
            # padded), and estimated_ttft_s prices a prompt as
            # ceil(P/CT) * CT * tok_s — dividing a short last chunk's
            # wall time by its few valid tokens would inflate the EWMA
            # up to CT-fold and over-shed feasible deadlines. NOT
            # amortized by the row count either: weight streaming
            # dominates a chunk tick, so an n-row tick costs ~one
            # n=1 tick — dividing by n would teach the autotuner a
            # per-token cost it cannot reproduce on n=1 groups and
            # blow the TPOT SLO exactly when load thins out
            self._ewma_prefill_tok.update(t_wall / CT)

    def _release_slot(self, slot_idx: int):
        """Free a slot's blocks and reservation and zero its block
        table + host mirrors — the ONE teardown behind retire, preempt
        and wave-unwind (a new per-slot mirror array must be reset
        here, nowhere else)."""
        s = self._slots[slot_idx]
        for bid in s.blocks:
            self.pool.free(bid)
        s.hits = None           # slot objects linger on the prefill
                                # FIFO; drop the cache refs now
        if s.dblocks:           # draft proposer pages
            for bid in s.dblocks:
                self._draft_pool_blocks.free(bid)
            s.dblocks = []
        if self._draft_tables is not None:
            self._draft_tables[slot_idx][:] = SCRATCH_BLOCK
        if self._history is not None:
            self._history[slot_idx][:] = 0
        if self._spec_cap is not None:
            # a fresh occupant starts at the configured k, optimistic
            self._spec_cap[slot_idx] = self._spec_k
            self._spec_k_slot[slot_idx] = self._spec_k
            self._spec_acc_ewma[slot_idx] = _Ewma()
        self._reserved -= s.worst_blocks - s.ntab
        self._slots[slot_idx] = None
        self._tables[slot_idx][:] = SCRATCH_BLOCK
        self._positions[slot_idx] = 0
        self._toks[slot_idx] = 0
        self._counts[slot_idx] = 0
        self._dirty = True

    def _preempt_victim(self, rank: int, exclude) -> Optional[int]:
        """Slot index of the lowest-priority, loosest-deadline active
        slot with priority STRICTLY below ``rank`` (preemption never
        crosses within a class, so a preempted-then-requeued request
        can never preempt its preemptor back — no ping-pong). ``exclude``
        holds this tick's freshly admitted slots (their prefill has not
        run; there is nothing to resume from)."""
        best = best_key = None
        for i, s in enumerate(self._slots):
            if s is None or i in exclude or s.req.rank >= rank:
                continue
            slack = (float("inf") if s.deadline_at is None
                     else s.deadline_at)
            key = (s.req.rank, -slack)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _preempt(self, slot_idx: int):
        """Retire a slot back to the queue with its generated-so-far
        tokens: frees its blocks (bf16: after donating its full
        immutable blocks to the prefix cache, so resume re-prefill
        adopts instead of recomputing), releases its reservation, and
        requeues the request for a token-exact resume.

        ``offload=True`` (docs/SERVING.md §Hierarchical KV): the
        victim's blocks are GATHERED to a host-bound buffer before the
        slot tears down, so preemption becomes a block-table remap plus
        a background drain — resume scatters the bytes back instead of
        re-prefilling and replaying. The resume tokens are STILL
        captured: the parked KV is an accelerator, and any failure on
        the swap path falls back to the token-exact replay resume."""
        s = self._slots[slot_idx]
        req = s.req
        if s.prefilling:
            # mid-chunk victim: no tokens sampled yet — requeue with
            # whatever resume state it was admitted with (None for a
            # fresh request); its partial KV (and carry) are dropped
            # with the slot, and the chunked re-prefill recomputes them
            req._resume_tokens = s.resume
        else:
            req._resume_tokens = list(s.tokens)
            req._t_first = s.t_first
        swapped = (self.offload and not s.prefilling
                   and self._swap_out(slot_idx, s))
        if self.prefix_cache is not None and not self.kv_int8 \
                and not s.prefilling and not swapped:
            # feed = prompt + generated[:-1]: exactly the s.pos written
            # positions; its full blocks are append-proof and already
            # physically populated — cache them (the cache takes its own
            # refs) so the resume prefill mostly gathers instead of
            # recomputing
            full = s.pos // self.block_tokens
            if full:
                # tpu-lint: allow(host-sync): host token-list concat
                self.prefix_cache.insert(
                    np.concatenate([req.prompt, np.asarray(
                        s.tokens[:-1], np.int32)]),
                    0, block_ids=s.blocks[:full])
        self._release_slot(slot_idx)
        self._queue.push(req)
        self.stats["preemptions"] += 1
        self._metrics.counter("serving.preemptions").inc()
        # tpu-lint: allow(journal-coverage): preemption is NOT terminal
        # — the request requeues in-engine with its tokens, which the
        # router's periodic "progress" events keep mirroring
        self._tick_preempted.append(req.request_id)
        if self._dump_pending is None:
            self._dump_pending = "preemption"

    # --------------------------- hierarchical KV: host-tier swap paths
    def _swap_fn(self, kind: str):
        """Jitted whole-block gather/scatter (the ONE seam the host
        tier touches device KV through — ``ops.fused_decode.
        paged_block_gather/scatter``; the fused tick program itself is
        untouched, so every compile-set and donation pin holds)."""
        fn = self._swap_fns.get(kind)
        if fn is None:
            from paddle_tpu.ops.fused_decode import (paged_block_gather,
                                                     paged_block_scatter)
            fn = (jax.jit(paged_block_gather) if kind == "gather"
                  else jax.jit(paged_block_scatter, donate_argnums=(0,)))
            self._swap_fns[kind] = fn
        return fn

    def _swap_out(self, slot_idx: int, s: "_Slot") -> bool:
        """Gather the preemption victim's blocks into one device buffer
        bound for the host tier. Returns False — the caller keeps the
        legacy free(+donate)+recompute path — when the tier has no
        room, a fault fires, or the engine runs a draft proposer (the
        draft's own KV pages cannot be restored; recompute-on-resume
        is the correct fallback there).

        The gather output is an independent buffer, so the source
        blocks are free to reuse the moment the gather is DISPATCHED:
        single-stream ordering guarantees any later program's writes
        into re-issued blocks execute after this read. The D2H leg
        (``copy_to_host_async``) overlaps the following serving ticks;
        :meth:`_drain_swaps` lands the bytes next tick."""
        from paddle_tpu.resilience import faults as _faults
        n = len(s.blocks)
        if n == 0 or self._draft_tables is not None \
                or not self.host_store.reserve(n):
            return False
        try:
            fault = _faults.maybe_fire("offload.swap")
        except BaseException:
            # a raising fault downgrades to the legacy path — zero
            # loss: the resume tokens were captured before the attempt
            self.host_store.unreserve(n)
            return False
        m = _swap_bucket(n)
        bids = np.full(m, SCRATCH_BLOCK, np.int32)
        bids[:n] = s.blocks
        buf = self._swap_fn("gather")(self.kv_pool, self._up(bids))
        try:
            buf.copy_to_host_async()
        except Exception:   # noqa: BLE001 — overlap is best-effort
            pass
        if fault is not None and fault.kind == "hang":
            # inside the swap window: chaos SIGKILLs land mid-swap here
            time.sleep(float(fault.payload.get("seconds", 0.05)))
        # tpu-lint: allow(host-sync): _kv_scales is a host mirror
        pk = _Parked(s.req.request_id, buf, n,
                     (np.array(self._kv_scales[:, slot_idx, :])
                      if self.kv_int8 else None),
                     s.pos, s.tok, s.count, list(s.tokens),
                     s.worst_blocks, s.prefix_hit_blocks)
        self._parked[s.req.request_id] = pk
        self._tick_swapped_out.append(s.req.request_id)
        self.stats["swap_outs"] += 1
        self._metrics.counter("serving.offload.swap_outs").inc()
        return True

    def _drain_swaps(self):
        """Land completed swap-out gathers in the host tier — called at
        tick start, at least one dispatch after each gather, so the D2H
        already overlapped with the tick that preempted (lazy drain:
        the sync below observes a transfer that is effectively done)."""
        for pk in self._parked.values():
            if pk.dev is not None:
                self._drain_one(pk)

    def _drain_one(self, pk: "_Parked"):
        # tpu-lint: allow(host-sync): the host tier's classified D2H
        # seam — draining an async gather a previous tick dispatched
        buf = np.asarray(pk.dev)
        pk.dev = None
        # tpu-lint: allow(host-sync): host slice copy of the drained buf
        pk.host_ids = self.host_store.put(
            [np.ascontiguousarray(buf[:, c]) for c in range(pk.n)])
        nbytes = pk.n * self.block_bytes
        self.stats["swap_out_bytes"] += nbytes
        self._metrics.counter("serving.offload.swap_out_bytes").inc(
            nbytes)

    def _stage_parked(self, pk: "_Parked"):
        """Assemble a parked request's host blocks into one bucketed
        device upload (async ``device_put`` H2D — the scatter that
        consumes it synchronizes). Every stage is timed into the swap
        EWMA: the prefetch policy's probe-and-observe estimate."""
        t0 = time.perf_counter()
        m = _swap_bucket(pk.n)
        buf = np.zeros((self._num_layers, m, self.block_tokens,
                        2 * self._dkv), jnp.dtype(self.cache_dtype))
        for c, p in enumerate(self.host_store.get(pk.host_ids)):
            buf[:, c] = p
        dev = (self.layout.place(buf, self.layout.pool_spec())
               if self.layout is not None else jax.device_put(buf))
        nbytes = pk.n * self.block_bytes
        self.stats["swap_in_bytes"] += nbytes
        self._metrics.counter("serving.offload.swap_in_bytes").inc(
            nbytes)
        self._ewma_swap_s.update(time.perf_counter() - t0)
        return dev

    def _offload_prefetch(self):
        """Stage host-resident parked requests back to device AHEAD of
        admission (EWMA prediction, the ``chunk_autotune``
        probe-and-observe pattern): the base lookahead is
        ``offload_prefetch`` queue positions, widened by the predicted
        number of serving ticks one stage costs (swap EWMA / decode
        step EWMA) — when staging is slow relative to a tick, it must
        start earlier for the admit path to never block on a cold
        copy."""
        lead = self.offload_prefetch
        if self._ewma_swap_s.value is not None and self._ewma_step.value:
            lead += max(0, -(-int(self._ewma_swap_s.value * 1e6)
                             // max(int(self._ewma_step.value * 1e6), 1))
                        - 1)
        lead = min(lead, self.max_slots + self.offload_prefetch)
        for pos, req in enumerate(self._queue):
            if pos >= lead:
                break
            pk = self._parked.get(req.request_id)
            if pk is None or pk.host_ids is None \
                    or req.request_id in self._staged:
                continue
            self._staged[req.request_id] = self._stage_parked(pk)

    def _drop_parked(self, request_id: int):
        """Invalidate a request's host-tier state (consumed / shed /
        released / fault fallback): free its host blocks and staging.
        Safe to call for requests that were never parked."""
        pk = self._parked.pop(request_id, None)
        self._staged.pop(request_id, None)
        if pk is None:
            return
        if pk.dev is not None:
            pk.dev = None       # un-drained gather: just drop the buf
            self.host_store.unreserve(pk.n)
        elif pk.host_ids is not None:
            self.host_store.free(pk.host_ids)

    def _swap_in_admit(self, req: Request, pk: "_Parked",
                       wave_idx) -> str:
        """Admit a parked request by scattering its host-tier blocks
        into freshly allocated pool blocks and rebuilding the slot row
        DIRECTLY — no prefill program, no replay dispatches: the
        generated-position KV comes back bitwise (the parity matrix in
        tests/test_serving_offload.py pins it against uninterrupted
        generation). Returns ``"admitted"``, ``"blocked"``
        (head-of-line: no slot/blocks this tick) or ``"fallback"``
        (parked KV unusable — the caller runs the legacy token-exact
        re-prefill + replay resume)."""
        from paddle_tpu.resilience import faults as _faults
        if pk.dev is not None:
            # preempted and re-admitted inside one tick: the background
            # drain has not seen this gather yet — land it now
            self._drain_one(pk)
        try:
            fault = _faults.maybe_fire("offload.swap")
        except BaseException:
            self._drop_parked(req.request_id)
            return "fallback"
        worst = max(pk.worst_blocks, pk.n)
        n = pk.n
        while True:
            short = worst - (self.pool.free_blocks - self._reserved)
            if short <= 0:
                break
            # same reclaim ladder as the legacy admission path:
            # cached-but-idle prefix blocks first, then strictly
            # lower-priority victims
            if self.prefix_cache is not None \
                    and self.prefix_cache.evict_free(short):
                continue
            victim = self._preempt_victim(req.rank, wave_idx)
            if victim is None:
                return "blocked"
            self._preempt(victim)
        try:
            slot_idx = self._slots.index(None)
        except ValueError:
            victim = self._preempt_victim(req.rank, wave_idx)
            if victim is None:
                return "blocked"
            self._preempt(victim)
            slot_idx = victim
        self._queue.pop()
        req._resume_tokens = None       # consumed; _preempt re-sets
        staged = self._staged.pop(req.request_id, None)
        if staged is not None:
            buf = staged
            self.stats["prefetch_hits"] += 1
            self._metrics.counter("serving.offload.prefetch",
                                  outcome="hit").inc()
        else:
            buf = self._stage_parked(pk)
            self.stats["prefetch_misses"] += 1
            self._metrics.counter("serving.offload.prefetch",
                                  outcome="miss").inc()
        if fault is not None and fault.kind == "hang":
            # inside the swap window: chaos SIGKILLs land mid-swap here
            time.sleep(float(fault.payload.get("seconds", 0.05)))
        bids = self.pool.alloc(n)
        dbids = np.full(buf.shape[1], SCRATCH_BLOCK, np.int32)
        dbids[:n] = bids
        self.kv_pool = self._swap_fn("scatter")(
            self.kv_pool, self._up(dbids), buf)
        s = _Slot(req, worst, pk.prefix_hit_blocks, req.prompt, None)
        s.blocks = bids
        s.ntab = n
        s.pos = pk.pos
        s.tok = pk.tok
        s.count = pk.count
        s.tokens = list(pk.tokens)
        s.t_first = req._t_first
        row = self._tables[slot_idx]
        row[:] = SCRATCH_BLOCK
        row[:n] = bids
        self._positions[slot_idx] = s.pos
        self._toks[slot_idx] = s.tok
        self._seeds[slot_idx] = np.uint32(req.seed)
        self._counts[slot_idx] = s.count
        if self.kv_int8 and pk.scales is not None:
            self._kv_scales[:, slot_idx, :] = pk.scales
        if req.deadline_s is not None:
            s.deadline_at = req._t_submit + req.deadline_s
        if self._history is not None:
            # ngram proposer: same priming as _adopt_slot's resume
            # branch — prompt + generated[:-1], current last token
            # tpu-lint: allow(host-sync): host token-list concat
            hist = np.concatenate(
                [req.prompt, np.asarray(pk.tokens[:-1], np.int32)])
            self._history[slot_idx][:] = 0
            self._history[slot_idx, :len(hist)] = hist
            self._history[slot_idx,
                          min(len(hist), self.max_seq_len - 1)] = s.tok
        self._reserved += worst - n
        self._slots[slot_idx] = s
        self._dirty = True
        wave_idx.add(slot_idx)
        self._drop_parked(req.request_id)
        self._tick_admitted.append(req.request_id)
        self._tick_swapped_in.append(req.request_id)
        self.stats["requests_admitted"] += 1
        self.stats["requests_resumed"] += 1
        self.stats["swap_ins"] += 1
        # tpu-lint: allow(journal-coverage): swap-in resume is not
        # terminal; the router already journaled the re-placement
        # ("place") that queued this resume
        self._tick_resumed.append(req.request_id)
        r = self._metrics
        r.counter("serving.resumed").inc()
        r.counter("serving.offload.swap_ins").inc()
        return "admitted"

    def _admit(self):
        """Priority admission: while a slot and the head request's
        worst-case block reservation both fit, pop it into the current
        wave; the wave is grouped by prefill shape ``(R, s_pad)`` and
        each group runs as ONE batched prefill program. The queue is
        ordered (priority, submit order) and stays head-of-line WITHIN
        that order; when the head cannot be placed, strictly
        lower-priority slots are preempted (requeued resumable, never
        dropped) to make room — first for a slot, then for blocks.

        Chunked mode (``chunk_tokens``): admission only places slots —
        blocks reserved/allocated, cursor at the prefix depth — and
        queues them on the prefill FIFO; the chunk programs run one per
        tick from :meth:`_step_inner`, so admission cost stays bounded
        and no prefill program blocks the tick that admitted it."""
        if self.chunk_tokens is not None:
            wave = []
            wave_idx = set()
            try:
                self._collect_wave(wave, wave_idx)
                # same-bucket admissions form one _ChunkGroup — n rows
                # advance one chunk each per fused tick (wave batching)
                self._make_chunk_groups(wave)
            except BaseException:
                self._unwind_wave(wave)
                raise
            return
        while self._queue:
            wave = []           # (slot_idx, slot, hits, R, s_pad)
            wave_idx = set()    # slots admitted this wave: not preemptable
            try:
                self._collect_wave(wave, wave_idx)
            except BaseException:
                # a raising fault at a MID-wave admission pop (or any
                # error before the wave's prefill ran) must not leave
                # earlier same-wave slots active with unwritten KV — a
                # retried step() would decode them from position 0 over
                # garbage. Unwind every un-prefilled slot back to the
                # queue (resumable, like a preemption) and re-raise.
                self._unwind_wave(wave)
                raise
            if not wave:
                return
            self._dirty = True
            groups: Dict = {}
            for item in wave:
                groups.setdefault((item[3], item[4]), []).append(item)
            try:
                for (R, s_pad), grp in groups.items():
                    self._run_prefill_group(R, s_pad, grp)
            except BaseException:
                self._unwind_wave(wave)     # only count==0 slots unwind
                raise
            # an instantly-finished admission (eos/1-token budget on the
            # prefill sample) frees its slot — loop for the next wave

    def _unwind_wave(self, wave):
        """Return every slot in ``wave`` whose prefill never ran
        (``count == 0`` — no KV written, no tokens) to the queue,
        releasing its blocks and reservation; prefilled slots are fully
        valid actives and stay."""
        for slot_idx, slot, _hits, _R, _s_pad in wave:
            if slot.count != 0 or self._slots[slot_idx] is not slot:
                continue
            req = slot.req
            self._release_slot(slot_idx)
            req._resume_tokens = slot.resume
            self._queue.push(req)
            if req.request_id in self._tick_admitted:
                self._tick_admitted.remove(req.request_id)
                self.stats["requests_admitted"] -= 1
            if slot.resume and req.request_id in self._tick_resumed:
                self._tick_resumed.remove(req.request_id)
                self.stats["requests_resumed"] -= 1

    def _collect_wave(self, wave, wave_idx):
        """Pop admissible requests into ``wave`` (see :meth:`_admit`
        for the policy; :meth:`_unwind_wave` for the fault contract)."""
        from paddle_tpu.resilience import faults as _faults

        BT = self.block_tokens
        while self._queue:
            req = self._queue.peek()
            if self._parked:
                pk = self._parked.get(req.request_id)
                if pk is not None:
                    st = self._swap_in_admit(req, pk, wave_idx)
                    if st == "blocked":
                        break
                    if st == "admitted":
                        continue
                    # "fallback": the parked KV is gone — the legacy
                    # token-exact re-prefill + replay resume below
            rank = req.rank
            resume = req._resume_tokens
            # a resume prefills the PROMPT only — the same program and
            # inputs as its original admission, so the prompt KV is
            # bitwise the original's. Its generated tokens REPLAY
            # through the real decode step program afterwards
            # (_replay_resume): recomputing them through the batched
            # prefill forward rounds differently in the last bf16 ulp
            # than the per-token decode path that first produced them,
            # and one ulp is enough to flip a near-tie argmax — the
            # token-exact contract must not hinge on ties being rare
            feed = req.prompt
            P = len(feed)
            n_lookup = (P - 1) // BT
            hits = (self.prefix_cache.lookup(feed, n_lookup,
                                             record=False)
                    if self.prefix_cache is not None else [])
            # worst case covers the FINAL sequence (original prompt
            # + full budget) — identical for fresh and resumed
            # admissions, so a resume can always re-reserve what its
            # first admission could
            worst = -(-(len(req.prompt) + req.max_new_tokens - 1)
                      // BT)
            # bf16 hits ride the cached PHYSICAL blocks (refcount++,
            # no fresh allocation); int8 hits only skip prefill
            # FLOPs — the slot still allocates every prompt block,
            # so they don't reduce the worst-case reservation
            spare = 0 if self.kv_int8 else len(hits)
            short = worst - spare - (self.pool.free_blocks
                                     - self._reserved)
            if short > 0:
                # feasibility BEFORE destroying live work: preempting a
                # victim gains at most its full reservation (physical
                # blocks freed + blocks shifted to cache-only + the
                # unreserved tail = worst_blocks), and eviction at most
                # the cache-only blocks. If even that optimistic total
                # cannot cover the shortfall, the head cannot be placed
                # this tick — break with zero preemptions instead of
                # evicting every lower-priority slot for nothing.
                potential = sum(
                    s.worst_blocks for i, s in enumerate(self._slots)
                    if s is not None and i not in wave_idx
                    and s.req.rank < rank)
                if self.prefix_cache is not None:
                    potential += self.prefix_cache.evictable_count(
                        keep=hits)
                if short > potential:
                    break
            try:
                slot_idx = self._slots.index(None)
            except ValueError:
                victim = self._preempt_victim(rank, wave_idx)
                if victim is None:
                    break
                self._preempt(victim)
                slot_idx = victim
                if self.prefix_cache is not None:
                    # the preempt's cache insert may have LRU-evicted
                    # stale `hits` entries (their blocks are gone) and
                    # donated new shareable ones — re-probe before the
                    # hits are adopted
                    hits = self.prefix_cache.lookup(feed, n_lookup,
                                                    record=False)
                    spare = 0 if self.kv_int8 else len(hits)
            while True:
                short = (worst - spare
                         - (self.pool.free_blocks - self._reserved))
                if short <= 0:
                    break
                if self.prefix_cache is not None:
                    # cached-but-idle prefix blocks are reclaimable
                    # pool capacity — evict LRU entries (never this
                    # request's own hits) before preempting live work
                    if self.prefix_cache.evict_free(short, keep=hits):
                        continue
                victim = self._preempt_victim(rank, wave_idx)
                if victim is None:
                    break
                self._preempt(victim)
                if self.prefix_cache is not None:
                    # the victim donated its blocks to the cache —
                    # re-probe: the head may now share them
                    hits = self.prefix_cache.lookup(feed, n_lookup,
                                                    record=False)
                    spare = 0 if self.kv_int8 else len(hits)
            if short > 0:
                break       # head-of-line within priority order
            # fault site BEFORE the pop: a raising fault (the PR 4
            # injection contract for decode.dispatch) leaves the
            # request queued — a retried step() re-admits it; firing
            # after the pop would lose it (no queue, slot or result)
            _faults.maybe_fire("decode.dispatch")
            self._queue.pop()
            req._resume_tokens = None   # consumed; _preempt re-sets
            if self.prefix_cache is not None:
                self.prefix_cache.commit(hits, n_lookup)

            R = len(hits) * BT
            n0 = -(-P // BT)        # blocks covering the feed
            s_pad = -(-(P - R) // BT) * BT
            slot = _Slot(req, worst, len(hits), feed, resume)
            slot.R = R
            row = self._tables[slot_idx]
            row[:] = SCRATCH_BLOCK
            if self.kv_int8:
                slot.blocks = self.pool.alloc(n0)
            else:
                for e in hits:  # slot's own ref on shared blocks
                    self.pool.ref(e.block_id)
                slot.blocks = ([e.block_id for e in hits]
                               + self.pool.alloc(n0 - len(hits)))
            slot.ntab = n0
            if self.chunk_tokens is not None:
                # chunked: the mirror table row STAYS at scratch until
                # the last chunk lands — a decode append into a
                # half-written prompt block would corrupt it. Blocks
                # ride the group's device block-id table; _adopt_slot
                # publishes the row when the slot joins decode.
                # (_make_chunk_groups batches this wave into groups.)
                slot.prefilling = True
                slot.filled = R
                slot.hits = hits
                if req.deadline_s is not None:
                    # mid-prefill expiry must sweep chunked slots (a
                    # monolithic slot prefills the tick it is admitted)
                    slot.deadline_at = req._t_submit + req.deadline_s
            else:
                row[:n0] = slot.blocks
            self._reserved += worst - n0
            self._slots[slot_idx] = slot
            self._tick_admitted.append(req.request_id)
            self.stats["requests_admitted"] += 1
            if resume:
                self.stats["requests_resumed"] += 1
                # tpu-lint: allow(journal-coverage): resume admission is
                # not terminal; the router already journaled the
                # re-placement ("place") that queued this resume
                self._tick_resumed.append(req.request_id)
            wave.append((slot_idx, slot, hits, R, s_pad))
            wave_idx.add(slot_idx)

    def _run_prefill_group(self, R, s_pad, grp):
        """Run one batched prefill program and adopt each row's slot
        into the running decode batch. The whole group (program + host
        pulls + slot adoption) is timed as the step's wave-prefill
        segment."""
        t_pf0 = time.perf_counter()
        n = len(grp)
        BT = self.block_tokens
        L = self._num_layers
        hb = R // BT
        ids = np.zeros((n, s_pad), np.int32)
        last_idx = np.zeros(n, np.int32)
        seeds = np.zeros(n, np.uint32)
        valid = np.zeros(n, np.int32)
        for r, (slot_idx, slot, hits, _, _) in enumerate(grp):
            P = len(slot.feed)
            ids[r, :P - R] = slot.feed[R:]
            last_idx[r] = P - 1 - R
            seeds[r] = np.uint32(slot.req.seed)
            # int8 calibration runs over the ORIGINAL prompt positions
            # only — for a fresh request that is the whole feed; for a
            # resume it reproduces the scales the uninterrupted run
            # calibrated at ITS prefill (appends beyond the prompt were
            # quantized with prompt-only scales there too, so resume
            # stays token-exact)
            valid[r] = len(slot.req.prompt)
        fn, warm = self._prefill_wave_fn(R, s_pad, n)
        if self.kv_int8:
            new_bids = np.asarray([s.blocks for _, s, _, _, _ in grp],
                                  np.int32)                    # (n, n0)
            prefix = (self._up(np.stack(
                [np.concatenate([e.kv_host for e in hits], axis=1)
                 for _, _, hits, _, _ in grp], axis=1)) if hb
                else self._up(np.zeros((L, n, 0, 2 * self._dkv),
                                       np.float32).astype(jnp.bfloat16)))
            tok, self.kv_pool, lanes, kv_flat = fn(
                self.kv_pool, prefix, self._up(ids),
                self._up(last_idx), self._up(seeds),
                self._up(new_bids), self._up(valid))
            # tpu-lint: allow(host-sync): once-per-wave D2H — int8 scales
            lanes_np = np.asarray(lanes)
            # tpu-lint: allow(host-sync): once-per-wave D2H — the prefix
            # cache keeps exact bf16 host copies of int8 blocks
            kv_np = (np.asarray(kv_flat)
                     if self.prefix_cache is not None else None)
        else:
            new_bids = np.asarray(
                [s.blocks[hb:] for _, s, _, _, _ in grp], np.int32)
            prefix = (np.asarray([[e.block_id for e in hits]
                                  for _, _, hits, _, _ in grp], np.int32)
                      if hb else np.zeros((n, 0), np.int32))
            tok, self.kv_pool = fn(
                self.kv_pool, self._up(prefix), self._up(ids),
                self._up(last_idx), self._up(seeds),
                self._up(new_bids), self._up(valid))
            lanes_np = kv_np = None
        # tpu-lint: allow(host-sync): once-per-wave D2H — first tokens
        tok_np = np.asarray(tok)
        for r, (slot_idx, slot, hits, _, _) in enumerate(grp):
            self._adopt_slot(
                slot_idx, slot, int(tok_np[r]),
                None if lanes_np is None else lanes_np[:, r],
                None if kv_np is None else kv_np[:, r])
        self._tick_prefills.append((R, s_pad, n))
        t_grp = time.perf_counter() - t_pf0
        self._tick_prefill_s += t_grp
        if warm:        # compile spikes must not poison the estimator
            new_toks = sum(len(s.feed) - s.R for _, s, _, _, _ in grp)
            self._ewma_prefill_tok.update(t_grp / max(new_toks, 1))

    def _replay_resume(self, slot_idx: int, s: "_Slot"):
        """Replay a resumed request's generated-so-far tokens through
        the REAL decode step program, one forced token per dispatch,
        every other batch row masked against scratch. Recomputing those
        positions through the prefill forward would be cheaper (one
        program) but rounds differently in the last bf16 ulp than the
        per-token decode path that first produced them — and one ulp
        flips a near-tie argmax, a token-parity break the zero-loss
        contract cannot afford. Replaying the same program at the same
        positions with the same inputs reproduces the uninterrupted
        engine's KV bitwise (decode rows are batch-composition-
        invariant — the PR 5 join/leave parity property). Cost:
        ``len(resume) - 1`` dispatches per resume; resumes are
        preemption/failover events, not the hot path."""
        if len(s.resume) <= 1:
            return
        if self._step_fn is None:
            self._step_fn = self._build_step_fn()
        ms = self.max_slots
        for j, tok in enumerate(s.resume[:-1]):
            self._ensure_blocks(slot_idx)   # append position = s.pos
            # FRESH host arrays per dispatch — never mutate a numpy
            # buffer a previous jnp.asarray may still be transferring
            # (PJRT CPU uploads are ImmutableUntilTransferCompletes;
            # reusing-and-mutating one raced with the fused tick still
            # executing and fed a later iteration's token/position into
            # an earlier dispatch — a once-in-a-few-runs parity flip)
            tables = np.full((ms, self.max_blocks_per_slot),
                             SCRATCH_BLOCK, np.int32)
            positions = np.zeros(ms, np.int32)
            toks = np.zeros(ms, np.int32)
            seeds = np.zeros(ms, np.uint32)
            counts = np.zeros(ms, np.int32)
            seeds[slot_idx] = np.uint32(s.req.seed)
            tables[slot_idx, :s.ntab] = s.blocks
            positions[slot_idx] = s.pos
            toks[slot_idx] = int(tok)
            counts[slot_idx] = j + 1
            _nxt, self.kv_pool, _pos, _cnt = self._step_fn(
                self.kv_pool, self._up(tables),
                self._up(positions), self._up(toks),
                self._up(seeds), self._up(counts),
                self._up_scales())
            s.pos += 1
        n = len(s.resume) - 1
        self.stats["replay_tokens"] += n
        self._metrics.counter("serving.replay_tokens").inc(n)

    def _adopt_slot(self, slot_idx: int, s: "_Slot", tok: int,
                    lanes_row, kv_row):
        """Join a fully-prefilled slot to the running decode batch: the
        mirror table row and per-slot device-mirror state, resume/TTFT
        bookkeeping, int8 scales, the prefix-cache insert and instant
        finishes. The ONE adoption path behind both the monolithic wave
        (one call per wave row) and the chunked path (after a slot's
        last chunk) — parity between the two modes lives here.

        The prefill sample ``tok`` is a FRESH request's first GENERATED
        token (``stats["decode_tokens"]`` counts only decode-step
        tokens); a resumed slot's sample is discarded — its next token
        comes from the next decode step at ``fold_in(seed, count)``,
        exactly where the uninterrupted run's stream stood."""
        req = s.req
        P = len(s.feed)
        BT = self.block_tokens
        s.prefilling = False
        s.hits = None
        # publish the block-table row (the chunked path deferred it so
        # decode appends could not touch half-written prompt blocks)
        self._tables[slot_idx][:s.ntab] = s.blocks
        self._dirty = True
        if lanes_row is not None:
            self._kv_scales[:, slot_idx, :] = lanes_row
        s.pos = P
        r = self._metrics
        if s.resume:
            s.count = len(s.resume)
            s.tok = int(s.resume[-1])
            s.tokens = list(s.resume)
            # TTFT is measured once, at the ORIGINAL first token —
            # a preemption must not reset it (crash restore has no
            # surviving monotonic base; it restarts the clock)
            s.t_first = (req._t_first if req._t_first is not None
                         else time.perf_counter())
            # the prefill above covered the PROMPT only (bitwise the
            # original admission's program); the generated-so-far
            # tokens replay through the real decode step program so
            # the resumed KV is bitwise what the uninterrupted run
            # held — advances s.pos to P + count - 1
            self._replay_resume(slot_idx, s)
            r.counter("serving.resumed").inc()
        else:
            s.count = 1
            s.tok = int(tok)
            s.tokens = [s.tok]
            s.t_first = time.perf_counter()
            r.counter("serving.tokens_generated").inc()
        if req.deadline_s is not None and s.deadline_at is None:
            s.deadline_at = req._t_submit + req.deadline_s
        self._positions[slot_idx] = s.pos
        self._toks[slot_idx] = s.tok
        self._seeds[slot_idx] = np.uint32(req.seed)
        self._counts[slot_idx] = s.count
        if self._history is not None:
            # ngram proposer: the committed tokens are the prompt, the
            # replayed resume prefix, and the slot's current last token
            # — the suffix the device matcher extends
            # tpu-lint: allow(host-sync): host token-list concat
            hist = (s.feed if not s.resume else np.concatenate(
                [s.feed, np.asarray(s.resume[:-1], np.int32)]))
            self._history[slot_idx][:] = 0
            self._history[slot_idx, :len(hist)] = hist
            self._history[slot_idx,
                          min(len(hist), self.max_seq_len - 1)] = s.tok
        if self._draft_tables is not None:
            self._run_draft_prefill(slot_idx, s)
        self.stats["prefill_tokens"] += P - s.R
        self.stats["prefill_tokens_reused"] += s.R
        if self.prefix_cache is not None:
            # full feed blocks are append-proof (appends land at
            # pos >= P) — bf16 shares them as-is, copy-on-write by
            # construction; int8 keeps exact bf16 copies host-side.
            # Inserts land AFTER the prefill program so a same-wave
            # sibling can never hit blocks not yet written (it just
            # misses; the next wave sees the entries).
            nh = s.prefix_hit_blocks
            if self.kv_int8:
                if kv_row is not None:
                    # copy the slices: a view would pin the whole
                    # (L, cache_len, 2dkv) buffer per cached block
                    # tpu-lint: allow(host-sync): host slice copy
                    self.prefix_cache.insert(
                        s.feed, nh,
                        kv_host=[np.ascontiguousarray(
                            kv_row[:, c * BT:(c + 1) * BT])
                                 for c in range(nh, P // BT)])
            else:
                self.prefix_cache.insert(
                    s.feed, nh, block_ids=s.blocks[nh:P // BT])
        eos = self.eos_token_id
        if (eos is not None and s.tok == int(eos)) \
                or s.count >= req.max_new_tokens:
            self._retire(slot_idx,
                         "eos" if eos is not None
                         and s.tok == int(eos) else "length")

    # -------------------------------------------------------------- decode
    def _decode_body(self):
        """Trace-time DECODE half shared by the plain step program and
        the fused tick: one paged decode step for every slot, with an
        optional coscheduled prefill-chunk scatter folded into the same
        pool pass (``ops.fused_decode.fused_paged_tick_step``)."""
        from paddle_tpu.inference import _row_keys, _sample_logits
        from paddle_tpu.ops.fused_decode import fused_paged_tick_step

        meta, arch, int8 = self.meta, self.arch, self.kv_int8
        model, cos_tab, sin_tab = self.model, self._cos_tab, self._sin_tab
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        pos_cap = self.max_seq_len - 1
        # under mp the body runs INSIDE shard_map: each shard walks its
        # own heads over its own pool lanes (local counts), and the
        # fused op gathers at the o-proj boundary; mp=1 passes the full
        # counts and mp_axis=None — the byte-identical pre-mp trace
        mp_axis = self._mp_axis
        nh_loc = meta["num_heads"] // self._mp
        nkv_loc = meta["num_kv_heads"] // self._mp
        gather_stacked = self._gather_stacked

        def body(state, stacked, pool, tables, positions, toks, seeds,
                 counts, kv_scales, chunk_bids=None, chunk_kv=None):
            # embed/head come from the traced state (cheap gathers); the
            # stacked layer weights arrive prebuilt via `stacked`, so the
            # plan's own build_fused_params output is unused and XLA
            # dead-codes the per-step restacking away
            stacked = gather_stacked(stacked)
            plan_t = model.fused_decode_plan(state)
            blocks = plan_t.get("blocks")
            if int8 and blocks is not None:
                blocks = dict(blocks, cache_wbytes=1)
            x = plan_t["embed"](toks, positions)
            cos = jnp.take(cos_tab, positions, axis=0)
            sin = jnp.take(sin_tab, positions, axis=0)
            x, pool = fused_paged_tick_step(
                x, stacked, pool, tables, positions, cos, sin,
                num_heads=nh_loc,
                num_kv_heads=nkv_loc, eps=meta["eps"],
                rope_base=meta["rope_base"], arch=arch, blocks=blocks,
                kv_scales=kv_scales if int8 else None,
                chunk_bids=chunk_bids, chunk_kv=chunk_kv,
                mp_axis=mp_axis)
            with jax.named_scope("decode.sample"):
                keys = _row_keys(seeds)
                ki = jax.vmap(jax.random.fold_in)(keys, counts)
                nxt = _sample_logits(plan_t["head"](x), ki, temperature,
                                     top_k, top_p)
            # advance the per-slot state in-program so event-free steps
            # re-dispatch with NO host->device uploads; the clamp only
            # ever binds on retired rows (an active row's position is
            # bounded by its admission-checked worst case), keeping their
            # table lookups in range while they idle against scratch
            pos2 = jnp.minimum(positions + 1, pos_cap)
            return nxt, pool, pos2, counts + 1

        return body

    def _build_step_fn(self):
        body = self._decode_body()

        def impl(state, stacked, pool, tables, positions, toks, seeds,
                 counts, kv_scales):
            return body(state, stacked, pool, tables, positions, toks,
                        seeds, counts, kv_scales)

        # donate the pool: the reference path batches every layer's
        # append into ONE scatter (jax-0.4 CPU ignores donation, so each
        # scatter costs one full pool copy — per step, not per layer);
        # on TPU the Pallas kernel aliases the pool and donation skips
        # the defensive copy (per SHARD under mp — the donation_report
        # pin covers the sharded tick too)
        from jax.sharding import PartitionSpec as P
        lay = self.layout
        pspec = lay.pool_spec() if lay is not None else None
        in_specs = (P(), self._stacked_specs or P(), pspec,
                    P(), P(), P(), P(), P(),
                    lay.kv_scales_spec() if lay is not None else None)
        out_specs = (P(), pspec, P(), P())
        jitted = self._wrap_program(impl, in_specs, out_specs,
                                    donate_argnums=(2,))
        return _program_handle(jitted,
                               lambda: (self._state, self._stacked))

    # ------------------------------------------------- speculative decode
    def _prop_zero(self, K: int):
        """The (proposals, nprop) reset pair for tail width ``K`` —
        immutable device constants built once per width, so a dirty
        tick re-arms the proposer without compiling a zeros program."""
        z = self._prop_zeros.get(K)
        if z is None:
            z = (jnp.zeros((self.max_slots, K), jnp.int32),
                 jnp.zeros((self.max_slots,), jnp.int32))
            if self.layout is not None:
                z = self.layout.place_replicated(z)
            self._prop_zeros[K] = z
        return z

    def _nprop_full(self, K: int):
        """The draft proposer's constant all-``K`` proposal count."""
        a = self._nprop_fulls.get(K)
        if a is None:
            a = jnp.full((self.max_slots,), K, jnp.int32)
            if self.layout is not None:
                a = self.layout.place_replicated(a)
            self._nprop_fulls[K] = a
        return a

    def _current_spec_k(self, active) -> int:
        """This tick's verify-tail width: the configured k, or with
        adaptive speculation the MAX per-slot k over the active slots
        (one batched verify program serves every slot; slots below the
        max are capped through the device-side ``cap`` vector). 0 means
        the tick runs the plain per-token decode dispatch — the whole
        point of adapting down on a low-acceptance mix. A k=0 recovery
        probe temporarily raises a parked slot's CAP above its k, so
        the width is the max over both."""
        if not self.speculate.adaptive:
            return self._spec_k
        return int(max(max(int(self._spec_k_slot[i]),
                           int(self._spec_cap[i])) for i in active))

    def _maybe_probe(self, active):
        """k=0 recovery probing (runs at the top of every decode tick
        of an adaptive engine): a slot parked at ``k_min=0`` proposes
        nothing, so its acceptance EWMA would never observe again and
        the slot could never climb back when the mix turns favorable.
        Every ``adapt_every`` consecutive parked ticks, raise each
        parked active slot's proposal cap to ONE for a two-tick probe
        window — the first (dirty) tick re-zeroes the carried ngram
        proposals and primes the device matcher, the second verifies a
        real one-token proposal and feeds the EWMA (the draft proposer
        observes on both). ``serving.spec_k_probes`` counts probed
        slots; the cap drops back when the window closes unless
        ``_adapt_spec_k`` climbed the slot's k in between."""
        if self._probe_window > 0:
            # window survives only while a probed slot is still active
            # (a retirement mid-window resets its cap via
            # _release_slot; without this the window could never close
            # once every probed slot is gone and ticks turn plain)
            if any(self._slots[i] is not None
                   for i in self._probe_slots):
                return
            self._probe_window = 0
            self._probe_slots = []
            return
        parked = [i for i in active if self._spec_k_slot[i] == 0]
        if not parked:
            self._spec_probe_wait = 0
            return
        self._spec_probe_wait += 1
        if self._spec_probe_wait < self.speculate.adapt_every:
            return
        self._spec_probe_wait = 0
        self._probe_window = 2
        self._probe_slots = list(parked)
        for i in parked:
            self._spec_cap[i] = 1
        self._dirty = True
        self.stats["spec_k_probes"] += len(parked)
        self._metrics.counter("serving.spec_k_probes").inc(len(parked))

    def _close_probe_window(self):
        """End-of-spec-tick bookkeeping for an open probe window: when
        it closes, parked slots drop back to cap 0 — unless the adapt
        step just climbed their k (the probe's success case)."""
        if self._probe_window <= 0:
            return
        self._probe_window -= 1
        if self._probe_window:
            return
        changed = False
        for i in self._probe_slots:
            if self._slots[i] is not None \
                    and int(self._spec_cap[i]) \
                    != int(self._spec_k_slot[i]):
                self._spec_cap[i] = int(self._spec_k_slot[i])
                changed = True
        self._probe_slots = []
        if changed:
            self._dirty = True

    def _adapt_spec_k(self, active, acc_np, nprop_np):
        """Per-slot adaptive-k update off the acceptance EWMA (runs at
        the end of each speculative tick). A k change is an EVENT: the
        cap vector re-uploads and the carried proposals re-zero at the
        (possibly) new tail width on the next tick — steady ticks with
        a stable k stay 0-H2D."""
        sc = self.speculate
        K_eff = self._spec_k_eff
        for i in active:
            if self._slots[i] is None:      # retired in this tick's commit
                continue
            neff = min(int(nprop_np[i]), int(self._spec_cap[i]), K_eff)
            if neff > 0:
                self._spec_acc_ewma[i].update(int(acc_np[i]) / neff)
        self._spec_adapt_tick += 1
        if self._spec_adapt_tick % sc.adapt_every:
            return
        changed = False
        for i in active:
            if self._slots[i] is None:
                continue
            ew = self._spec_acc_ewma[i].value
            if ew is None:
                continue
            k_i = int(self._spec_k_slot[i])
            if ew < sc.acceptance_floor and k_i > sc.k_min:
                k_i -= 1
            elif ew > sc.acceptance_ceiling and k_i < sc.k:
                k_i += 1
            else:
                continue
            self._spec_k_slot[i] = k_i
            self._spec_cap[i] = k_i
            changed = True
        if changed:
            self._dirty = True

    def _verify_body(self, K: int):
        """Trace-time VERIFY half shared by the speculative step
        program and the fused tick (see :meth:`_build_verify_fn` for
        the acceptance contract): an optional coscheduled prefill-chunk
        scatter folds into the same pool pass before the verify walk."""
        from paddle_tpu.inference import _row_keys, _sample_logits
        from paddle_tpu.ops.fused_decode import (fused_paged_verify_step,
                                                 paged_chunk_scatter)
        from paddle_tpu.serving.spec import ngram_propose

        meta, arch, int8 = self.meta, self.arch, self.kv_int8
        model, cos_tab, sin_tab = self.model, self._cos_tab, self._sin_tab
        temperature, top_k, top_p = (self.temperature, self.top_k,
                                     self.top_p)
        pos_cap = self.max_seq_len - 1
        K1 = K + 1
        ngram = self.speculate.proposer == "ngram"
        nmax = self.speculate.ngram_max
        nmin = self.speculate.ngram_min
        mp_axis = self._mp_axis
        nh_loc = meta["num_heads"] // self._mp
        nkv_loc = meta["num_kv_heads"] // self._mp
        gather_stacked = self._gather_stacked

        def body(state, stacked, pool, tables, positions, toks, seeds,
                 counts, kv_scales, proposals, nprop, cap, history=None,
                 chunk_bids=None, chunk_kv=None):
            stacked = gather_stacked(stacked)
            if chunk_bids is not None:
                if mp_axis is not None \
                        and chunk_kv.shape[-1] != pool.shape[-1]:
                    # the chunk half hands over CANONICAL-width payload
                    # (the replicated full-model forward); keep this
                    # shard's own [k_s|v_s] lanes before the scatter
                    chunk_kv = mp_local_kv_lastdim(chunk_kv, mp_axis)
                with jax.named_scope("fused_decode.chunk_scatter"):
                    pool = paged_chunk_scatter(pool, chunk_bids, chunk_kv)
            plan_t = model.fused_decode_plan(state)
            blocks = plan_t.get("blocks")
            if int8 and blocks is not None:
                blocks = dict(blocks, cache_wbytes=1)
            tail = jnp.concatenate([toks[:, None], proposals], axis=1)
            xs, coss, sins = [], [], []
            for j in range(K1):
                # per-token embed/rope rows, shaped exactly like the
                # plain step's (the clamp binds only on over-speculation
                # past a retiring slot's cap — garbage rows)
                pj = jnp.minimum(positions + j, pos_cap)
                xs.append(plan_t["embed"](tail[:, j], pj))
                coss.append(jnp.take(cos_tab, pj, axis=0))
                sins.append(jnp.take(sin_tab, pj, axis=0))
            x = jnp.stack(xs, axis=1)                     # (b, K1, h)
            x, pool = fused_paged_verify_step(
                x, stacked, pool, tables, positions,
                jnp.stack(coss, axis=1), jnp.stack(sins, axis=1),
                num_heads=nh_loc,
                num_kv_heads=nkv_loc, eps=meta["eps"],
                rope_base=meta["rope_base"], arch=arch, blocks=blocks,
                kv_scales=kv_scales if int8 else None, mp_axis=mp_axis)
            keys = _row_keys(seeds)
            gs = []
            for j in range(K1):
                with jax.named_scope("decode.sample"):
                    # the exact key the non-speculative engine folds for
                    # token count+j — sample-and-match acceptance is
                    # what makes speculation bit-invisible
                    ki = jax.vmap(jax.random.fold_in)(keys, counts + j)
                    gs.append(_sample_logits(plan_t["head"](x[:, j]), ki,
                                             temperature, top_k, top_p))
            g = jnp.stack(gs, axis=1)                     # (b, K1)
            # per-slot proposal cap: the adaptive-k vector (full k when
            # adaptivity is off — the clamp is then a no-op)
            nprop_eff = jnp.minimum(jnp.minimum(nprop, cap), K)
            match = (proposals == g[:, :K]) \
                & (jnp.arange(K)[None] < nprop_eff[:, None])
            acc = jnp.cumprod(match.astype(jnp.int32),
                              axis=1).sum(axis=1)         # (b,)
            tok2 = jnp.take_along_axis(g, acc[:, None], axis=1)[:, 0]
            pos2 = jnp.minimum(positions + acc + 1, pos_cap)
            counts2 = counts + acc + 1
            if not ngram:
                return g, acc, pool, pos2, tok2, counts2
            # committed-token history: the tail lands at its absolute
            # indices, then the corrected/bonus token at pos2 — writes
            # past the accepted prefix are stale and sit beyond the
            # committed length, exactly like rejected KV
            rows = jnp.arange(tail.shape[0])
            idxm = jnp.minimum(
                positions[:, None] + jnp.arange(K1)[None], pos_cap)
            hist2 = history.at[rows[:, None], idxm].set(tail)
            hist2 = hist2.at[rows, pos2].set(tok2)
            prop2, nprop2 = ngram_propose(hist2, pos2 + 1, K, nmax, nmin)
            return (g, acc, pool, pos2, tok2, counts2, hist2, prop2,
                    jnp.minimum(nprop2, cap))

        return body

    def _build_verify_fn(self, K: int):
        """ONE program per speculative tick: embed the K+1-token tail
        (last sampled token + K proposals) per slot, score it through
        ``fused_paged_verify_step`` (KV appended through the multi-token
        path), sample each position's TARGET token off the slot's own
        ``fold_in(seed, count + j)`` stream, and accept the longest
        proposal prefix that matches — token-exact, so committed tokens
        are bitwise the non-speculative engine's. Per-slot state
        (positions/counts/last token) advances on device, and for the
        n-gram proposer the committed-token history and the NEXT tick's
        proposals are produced in the same program — a steady
        speculative tick re-dispatches with zero H2D uploads."""
        body = self._verify_body(K)
        ngram = self.speculate.proposer == "ngram"

        def impl(state, stacked, pool, tables, positions, toks, seeds,
                 counts, kv_scales, proposals, nprop, cap, *hist):
            return body(state, stacked, pool, tables, positions, toks,
                        seeds, counts, kv_scales, proposals, nprop, cap,
                        hist[0] if ngram else None)

        # donate the history buffer alongside the pool: the ngram path
        # RMWs it every verify tick (hist2 = history.at[...].set) and
        # the caller rebinds self._dev_hist from the output, so the old
        # buffer is dead at dispatch — undonated it cost one full
        # (max_slots, max_seq_len) copy per speculative tick (the
        # donation lint rule's first catch; donation_report pins it)
        from jax.sharding import PartitionSpec as P
        lay = self.layout
        pspec = lay.pool_spec() if lay is not None else None
        in_specs = ((P(), self._stacked_specs or P(), pspec)
                    + (P(),) * 5
                    + (lay.kv_scales_spec() if lay is not None else None,)
                    + (P(),) * 3 + ((P(),) if ngram else ()))
        out_specs = [P()] * (9 if ngram else 6)
        out_specs[2] = pspec
        jitted = self._wrap_program(
            impl, in_specs, tuple(out_specs),
            donate_argnums=(2,) + ((12,) if ngram else ()))
        return _program_handle(jitted,
                               lambda: (self._state, self._stacked))

    def _build_draft_fn(self, K: int):
        """Draft-proposer round: ONE scanned program runs k+1 greedy
        draft decode steps over the draft's own paged pool (positions
        shared with the target — draft and target appends advance in
        lockstep). k+1 appends, not k: the step that appends the k-th
        proposal's KV is what keeps the draft gap-free when the whole
        proposal is accepted (the bonus token's predecessor must be in
        the draft cache before the next round). Returns the k proposals
        and the updated draft pool; proposals stay on device — the
        verify program reads them directly, the host pulls them with
        the accepted counts after verify."""
        from paddle_tpu.inference import _sample_logits
        from paddle_tpu.ops.fused_decode import fused_paged_decode_step

        dm = self.speculate.draft_model
        dmeta = self._draft_meta
        darch = self._draft_arch
        pos_cap = self.max_seq_len - 1
        cos_tab, sin_tab = self._draft_cos, self._draft_sin

        def impl(dstate, dstacked, dpool, dtables, positions, toks):
            plan_t = dm.fused_decode_plan(dstate)
            blocks = plan_t.get("blocks")

            # NOT named `step`: the tpu-lint callgraph resolves bare
            # names module-wide, and a lax.scan body called `step`
            # would mark ServingEngine.step as jit-reachable
            def draft_step(carry, _):
                tok, pool, pos = carry
                x = plan_t["embed"](tok, pos)
                cos = jnp.take(cos_tab, pos, axis=0)
                sin = jnp.take(sin_tab, pos, axis=0)
                x, pool = fused_paged_decode_step(
                    x, dstacked, pool, dtables, pos, cos, sin,
                    num_heads=dmeta["num_heads"],
                    num_kv_heads=dmeta["num_kv_heads"],
                    eps=dmeta["eps"], rope_base=dmeta["rope_base"],
                    arch=darch, blocks=blocks, kv_scales=None)
                with jax.named_scope("decode.draft_sample"):
                    # greedy proposals: acceptance is exact-match
                    # against the target's sample, so the draft's best
                    # guess is its own argmax — no draft RNG stream
                    nxt = _sample_logits(plan_t["head"](x), None,
                                         0.0, 0, 1.0)
                return (nxt, pool, jnp.minimum(pos + 1, pos_cap)), nxt

            (_, pool, _), props = jax.lax.scan(
                draft_step, (toks, dpool, positions), None, length=K + 1)
            return props[:K].T.astype(jnp.int32), pool

        # the draft runs fully REPLICATED under mp (every spec is P());
        # the shard_map wrap still matters — it pins the draft's inputs
        # and outputs to the mesh so a speculative tick never mixes
        # mesh-committed and single-device buffers
        from jax.sharding import PartitionSpec as P
        jitted = self._wrap_program(impl, (P(),) * 6, (P(), P()),
                                    donate_argnums=(2,))
        return _program_handle(
            jitted, lambda: (self._draft_state, self._draft_stacked))

    def _draft_prefill_fn(self, s_pad):
        """Draft prefill program (keyed by padded feed length, like the
        target's prefill buckets): forward the feed through the draft
        model and scatter its KV into the slot's draft pages. No
        sampling, no calibration (the draft pool is always bf16) — the
        draft is a proposer, its logits only matter during rounds.
        Returns ``(fn, cached)``."""
        from paddle_tpu.nn.layer import functional_call

        key = ("draft_prefill", s_pad)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn, True
        dm = self.speculate.draft_model
        BT = self.block_tokens
        nb = s_pad // BT
        Ld = self._draft_layers
        dkv = self._draft_dkv

        def impl(dstate, pool, ids, new_bids):
            cache = dm.init_cache(1, s_pad, dtype=jnp.bfloat16)
            with jax.named_scope("decode.draft_prefill"):
                _, cache = functional_call(dm, dstate, ids, cache=cache,
                                           start_pos=0)
            kv_flat = jnp.stack([jnp.concatenate(
                [c["k"].reshape(1, s_pad, dkv),
                 c["v"].reshape(1, s_pad, dkv)], axis=-1)
                for c in cache])             # (Ld, 1, s_pad, 2dkv)
            blk = kv_flat.reshape(Ld, 1, nb, BT, 2 * dkv)
            return pool.at[:, new_bids].set(blk.astype(pool.dtype))

        from jax.sharding import PartitionSpec as P
        jitted = self._wrap_program(impl, (P(),) * 4, P(),
                                    donate_argnums=(1,))
        fn = _program_handle(jitted, lambda: (self._draft_state,))
        self._jit_cache[key] = fn
        return fn, False

    def _run_draft_prefill(self, slot_idx: int, s: "_Slot"):
        """Fill the draft's KV pages for a freshly adopted slot (called
        from :meth:`_adopt_slot` — the one join path, so chunked and
        monolithic admissions both land here). The draft prefill is
        monolithic even on chunked engines: the draft is small by
        contract, so one program over the whole feed doesn't move the
        chunked TPOT bound the way a target prefill would."""
        # the draft rides the FULL committed context (prompt + replayed
        # resume tokens): its KV is advisory — proposals only, the
        # target's sample-match acceptance decides tokens — so the
        # batched prefill recompute is fine here in a way it is not
        # for the target's resumed KV (see _replay_resume)
        # tpu-lint: allow(host-sync): host token-list concat
        feed = (s.feed if not s.resume else np.concatenate(
            [s.feed, np.asarray(s.resume[:-1], np.int32)]))
        P = len(feed)
        BT = self.block_tokens
        dn0 = -(-P // BT)
        fresh = self._draft_pool_blocks.alloc(dn0 - len(s.dblocks))
        self._draft_tables[slot_idx, len(s.dblocks):dn0] = fresh
        s.dblocks.extend(fresh)
        ids = np.zeros((1, dn0 * BT), np.int32)
        ids[0, :P] = feed
        fn, _cached = self._draft_prefill_fn(dn0 * BT)
        self.draft_kv_pool = fn(
            self.draft_kv_pool, self._up(ids),
            self._up(np.asarray([s.dblocks[:dn0]], np.int32)))

    def _ensure_blocks(self, slot_idx: int, horizon: int = 0):
        """Append positions [pos, pos+horizon] must resolve to allocated
        blocks; allocate lazily as a slot's sequence crosses block
        boundaries (admission already reserved the worst case, so this
        cannot exhaust the pool). ``horizon`` is the speculative append
        depth (k tail tokens beyond the base append); allocation never
        exceeds the slot's reservation — over-speculation past it lands
        in the scratch block by table construction."""
        s = self._slots[slot_idx]
        c = min((s.pos + horizon) // self.block_tokens,
                s.worst_blocks - 1)
        while s.ntab <= c:
            bid = self.pool.alloc(1)[0]
            s.blocks.append(bid)
            self._tables[slot_idx][s.ntab] = bid
            s.ntab += 1
            self._reserved -= 1
            self._dirty = True

    def _ensure_draft_blocks(self, slot_idx: int):
        """Draft-proposer twin of :meth:`_ensure_blocks`: the draft
        appends k+1 tokens per tick at the target's positions, against
        its own worst-case-sized pool (allocation cannot fail)."""
        s = self._slots[slot_idx]
        c = min((s.pos + self._spec_k) // self.block_tokens,
                self.max_blocks_per_slot - 1)
        while len(s.dblocks) <= c:
            bid = self._draft_pool_blocks.alloc(1)[0]
            self._draft_tables[slot_idx][len(s.dblocks)] = bid
            s.dblocks.append(bid)
            self._dirty = True

    def _retire(self, slot_idx: int, finish: str):
        from paddle_tpu import observability as obs

        s = self._slots[slot_idx]
        now = time.perf_counter()
        self._release_slot(slot_idx)

        # a slot swept mid-prefill (chunked engines: deadline expiry
        # before its last chunk) has no sampled tokens yet — it retires
        # with what a preemption would have preserved (the resume
        # tokens, for a request cut while re-prefilling)
        raw = s.tokens if not s.prefilling else (s.resume or [])
        # tpu-lint: allow(host-sync): generated tokens are a host list
        toks = np.asarray(raw, np.int32)
        eos = self.eos_token_id
        if eos is not None and (toks == int(eos)).any():
            gen_len = int((toks == int(eos)).argmax())
        else:
            gen_len = len(toks)
        if s.t_first is not None:
            ttft = s.t_first - s.req._t_submit
        elif s.req._t_first is not None and s.req._t_submit is not None:
            # preempted-then-resumed, cut mid-re-prefill: TTFT is still
            # the ORIGINAL first token (same rule as _shed_queued)
            ttft = s.req._t_first - s.req._t_submit
        else:
            ttft = None
        tpot = ((now - s.t_first) / (s.count - 1) if s.count > 1 else None)
        # tpu-lint: allow(journal-coverage): THE engine finish site —
        # the Router journals "finish" when it collects this result
        # from step(); single-engine durability is the snapshot, which
        # serializes results
        res = RequestResult(s.req.request_id, s.req.prompt, toks, gen_len,
                            finish, ttft, tpot, s.prefix_hit_blocks,
                            trace_id=s.req.trace_id)
        self.results[s.req.request_id] = res
        self._finished_tick.append(s.req.request_id)
        self._tick_retired.append((s.req.request_id, finish))
        self.stats["requests_finished"] += 1
        r = self._metrics
        r.counter("serving.requests", finish=finish).inc()
        # the SLO percentile layer: per-request TTFT/TPOT land in
        # bounded-relative-error sketches (docs/OBSERVABILITY.md)
        if ttft is not None:
            r.sketch("serving.ttft_s").observe(ttft)
        if tpot is not None:
            r.sketch("serving.tpot_s").observe(tpot)
        if finish == "deadline":
            # postmortem seam: snapshot the flight ring once this tick's
            # event (the one recording this retirement) has been written
            self._dump_pending = "deadline_retirement"
        tr = obs.active_tracer()
        if tr is not None:
            # _t_submit is monotonic (perf_counter); span ts must share
            # the wall-clock base every other span uses, so map the
            # monotonic age onto time.time() at retirement
            tr.record("serving.request",
                      ts=time.time() - (now - s.req._t_submit),
                      dur_s=now - s.req._t_submit,
                      request_id=s.req.request_id,
                      trace_id=s.req.trace_id, finish=finish,
                      prompt_len=int(len(s.req.prompt)),
                      tokens=int(s.count), ttft_s=ttft, tpot_s=tpot,
                      prefix_hit_blocks=s.prefix_hit_blocks)
        return res

    def step(self) -> Dict:
        """One scheduler tick: admit what fits, retire expired deadlines,
        run ONE fused paged decode step for every active slot, retire
        slots that finished. Returns a small status dict.

        Each tick is wall-timed in four segments — admit (scheduling +
        deadline sweep + block-table bookkeeping), wave-prefill, fused
        decode dispatch (program call; on async backends this is enqueue
        time), host sync (the sampled-token D2H pull, where device wait
        surfaces) — recorded into the ``serving.step_*_s`` histograms,
        the cumulative ``stats["step_*_s"]`` fields and this tick's
        flight-recorder event, so a TPOT spike is attributable to a
        phase. A tick that dies mid-flight (injected fault,
        ``PoolExhausted``) still records a partial event carrying the
        error, auto-dumps the ring, and re-raises.
        """
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        # shed events between ticks (submit-time displacement) surface
        # in THIS tick's finished list — step()['finished'] stays the
        # complete result-collection contract
        self._finished_tick = list(self._pending_finished)
        self._pending_finished = []
        self._tick_admitted = []
        self._tick_retired = []
        self._tick_prefills = []
        self._tick_chunks = []
        self._tick_prefill_s = 0.0
        self._tick_preempted = []
        self._tick_resumed = []
        self._tick_swapped_out = []
        self._tick_swapped_in = []
        self._tick_spec = None
        # _tick_shed keeps accumulating across submit() calls between
        # ticks; _record_flight drains it into this tick's event
        t0 = time.perf_counter()
        try:
            return self._step_inner(t0)
        except Exception as e:
            admit_s = max(0.0,
                          time.perf_counter() - t0 - self._tick_prefill_s)
            self._record_flight(admit_s, None, None,
                                err=f"{type(e).__name__}: {e}")
            self.flight.auto_dump(f"error:{type(e).__name__}")
            # the error dump supersedes any dump this tick queued (e.g.
            # a deadline retirement swept just before the dispatch died)
            # — without this, the NEXT successful tick would emit a
            # spurious "deadline_retirement" dump
            self._dump_pending = None
            raise

    def _step_inner(self, t0: float) -> Dict:
        from paddle_tpu.resilience import faults as _faults
        from paddle_tpu.resilience import record_event

        # host-tier housekeeping BEFORE admission: land last tick's
        # swap-out gathers and stage predicted swap-ins (both gated on
        # parked work existing, so an offload-enabled engine with
        # nothing parked runs the exact steady tick — the 0-H2D pin in
        # tests/test_analysis.py covers offload=True idle ticks)
        if self._parked:
            self._drain_swaps()
            self._offload_prefetch()
        # every _retire this tick (deadline sweep, instant finish on the
        # prefill sample inside _admit, decode finish) lands in
        # _finished_tick, so the returned `finished` list is complete
        # for result collection
        self._admit()
        now = time.perf_counter()
        for i, s in enumerate(self._slots):
            if s is not None and s.deadline_at is not None \
                    and now > s.deadline_at:
                record_event("deadline_exceeded")
                self._retire(i, "deadline")
        # chunked-prefill interleave (the ONE-PROGRAM tick): when a
        # chunk is due — every `decode_per_chunk` decode dispatches
        # while decode-ready slots exist, unconditionally otherwise —
        # the tick dispatches ONE fused program computing the front
        # group's next chunk AND every decode-ready slot's next token
        # (or verify tail), so the decode TPOT bound is one fused tick
        # and the pool/carry cross exactly one program boundary.
        grp = None
        if self.chunk_tokens is not None:
            front = self._front_prefill()
            if front is not None:
                decode_ready = any(s is not None and not s.prefilling
                                   for s in self._slots)
                if (not decode_ready
                        or self._decode_since_chunk
                        >= self.decode_per_chunk):
                    grp = front
        dispatch_s = sync_s = None
        spec = self.speculate is not None
        spec_tick = False
        K_eff = 0
        # prefilling slots stay OUT of the decode batch: their mirror
        # rows idle against scratch until the last chunk adopts them
        active = [i for i, s in enumerate(self._slots)
                  if s is not None and not s.prefilling]
        if active or grp is not None:
            if spec and active:
                if self.speculate.adaptive:
                    self._maybe_probe(active)
                self._spec_k_eff = K_eff = self._current_spec_k(active)
                spec_tick = K_eff > 0
                if K_eff != self._last_spec_k:
                    # a changed verify-tail width is an EVENT tick: the
                    # carried proposals re-zero at the new width and the
                    # mirrors (incl. the per-slot cap) re-upload
                    self._dirty = True
                    self._last_spec_k = K_eff
            if spec_tick:
                if K_eff not in self._verify_fns:
                    self._verify_fns[K_eff] = self._build_verify_fn(K_eff)
                    if self.speculate.proposer == "draft":
                        self._draft_fns[K_eff] = self._build_draft_fn(
                            K_eff)
            elif self._step_fn is None and grp is None:
                # non-speculative engines AND adaptive ticks whose every
                # active slot sits at k=0 ride the plain per-token
                # dispatch — the "stops paying the verify tail" case
                self._step_fn = self._build_step_fn()
            for i in active:
                self._ensure_blocks(i, self._spec_k if spec else 0)
                if self._draft_tables is not None:
                    self._ensure_draft_blocks(i)
            _faults.maybe_fire("decode.dispatch")
            # the fused tick program for this chunk bucket (cursor +
            # tail width); built before the steady/dirty decision so a
            # compile never counts as a steady dispatch
            tick_fn = None
            tick_warm = True
            g_start = g_kind = None
            if grp is not None:
                g_start, g_kind = grp.start, grp.kind
                tick_fn, tick_warm = self._tick_fn(
                    g_kind, g_start, grp.n, grp.C_pad, grp.chunk, grp.R,
                    K_eff if spec_tick else 0)
            # steady state = the warm program re-dispatches with NO
            # host->device upload: no join/leave/lazy-block event made
            # the mirrors dirty. This is the tick the "no steady-state
            # H2D" claim is about — and what sanitize mode guards.
            # Steady FUSED ticks (mid-prefill chunks of a covered
            # bucket) hold the same invariant: every chunk input is
            # device-resident from admission.
            steady = self._step_fn_warm and not self._dirty and tick_warm
            if self._dirty:
                self._dev = (self._up(self._tables),
                             self._up(self._positions),
                             self._up(self._toks),
                             self._up(self._seeds),
                             self._up(self._counts),
                             self._up_scales())
                if self._history is not None:
                    self._dev_hist = self._up(self._history)
                    # a join/leave tick drops the carried proposals —
                    # the device matcher re-primes them at the end of
                    # this tick's verify (one plain-decode tick per
                    # event, never a wrong speculation)
                    self._dev_prop = (self._prop_zero(self._spec_k_eff)
                                      if spec_tick else None)
                if spec:
                    self._dev_cap = self._up(self._spec_cap)
                if self._draft_tables is not None:
                    self._draft_dev = self._up(self._draft_tables)
                self._dirty = False
        # everything up to the dispatch call is the admit segment
        # (minus the prefill programs, which _run_prefill_group timed)
        admit_s = max(0.0, time.perf_counter() - t0 - self._tick_prefill_s)
        if spec_tick:
            dispatch_s, sync_s = self._spec_decode(
                active, steady, grp, tick_fn, tick_warm, g_start, g_kind)
        elif active or grp is not None:
            dispatch_s, sync_s = self._plain_decode(
                active, steady, grp, tick_fn, tick_warm, g_start, g_kind)
        self._record_segments(admit_s, dispatch_s, sync_s)
        self._record_flight(admit_s, dispatch_s, sync_s)
        self._after_flight()
        return dict(active=self.active_slots, queued=len(self._queue),
                    finished=self._finished_tick)

    def _select_chunk_outs(self, grp, g_kind, chunk_outs):
        """Split the chunk half's outputs off a fused-tick result —
        the ONE place that re-implements ``_tick_fn``'s output
        ordering (carry2 | ctok [, lanes [, kvfull]], each present
        only when the bucket produces it). Mid int8 ticks rebind the
        group's resident carry in place; returns ``(ctok, lanes,
        kvfull)`` as device arrays still to fence/pull (``None`` where
        absent)."""
        ctok = lanes = kvfull = None
        if grp is not None:
            if g_kind == "last":
                ctok = chunk_outs[0]
                if self.kv_int8:
                    lanes = chunk_outs[1]
                    if self.prefix_cache is not None:
                        kvfull = chunk_outs[2]
            elif self.kv_int8:
                grp.carry = chunk_outs[0]   # the resident carry, RMW'd
        return ctok, lanes, kvfull

    def _fence_chunk_pulls(self, grp, g_kind, chunk_outs, head):
        """THE tick's one per-step D2H completion fence plus the chunk
        half's host-pull choreography, shared by the plain and
        speculative paths: select the chunk outputs off the fused
        result (:meth:`_select_chunk_outs`), pull ``head`` (the decode
        half's host-needed arrays; ``None`` entries skipped) and any
        chunk outputs in ONE batched ``device_get`` — not N round
        trips on the sync segment the TPOT bound measures — and reset
        the interleave budget (the fused tick IS this window's chunk;
        its own decode half counts toward the budget through the
        caller's increment — reset-then-increment, the two-program
        tick's order). A chunk-only mid tick has no host-needed
        output: fence on the carry (int8) or the pool (bf16 — the
        chunk scattered into it) instead, so the wall time the caller
        reads still measures completion, not dispatch (the chunk
        EWMAs/stall trigger would otherwise go blind). Returns
        ``(head_np, ctok_np, lanes_np, kvfull_np)``, ``head_np``
        mirroring ``head`` entry for entry."""
        ctok, lanes, kvfull = self._select_chunk_outs(grp, g_kind,
                                                      chunk_outs)
        pulls = [x for x in (*head, ctok, lanes, kvfull)
                 if x is not None]
        if pulls:
            # tpu-lint: allow(host-sync): the per-step D2H completion
            # fence (one batched device_get, not N round trips)
            pulled = list(jax.device_get(tuple(pulls)))
        else:
            fence = (grp.carry if grp is not None
                     and grp.carry is not None else self.kv_pool)
            # tpu-lint: allow(host-sync): the mid-chunk completion
            # fence
            fence.block_until_ready()
            pulled = []
        head_np = [pulled.pop(0) if h is not None else None
                   for h in head]
        ctok_np = pulled.pop(0) if ctok is not None else None
        lanes_np = pulled.pop(0) if lanes is not None else None
        kvfull_np = pulled.pop(0) if kvfull is not None else None
        if grp is not None:
            self._decode_since_chunk = 0
        return head_np, ctok_np, lanes_np, kvfull_np

    def _plain_decode(self, active, steady, grp=None, tick_fn=None,
                      tick_warm=True, g_start=None, g_kind=None):
        """One plain (non-speculative) tick's dispatch + host commit:
        the fused tick program when a chunk is due (``grp``), else the
        per-token step program. Returns (dispatch_s, sync_s)."""
        t_d0 = time.perf_counter()
        if grp is not None:
            fn = tick_fn
            args = (self.kv_pool, *grp.args(), *self._dev)
        else:
            fn = self._step_fn
            args = (self.kv_pool, *self._dev)
        if self._sanitize and steady:
            from paddle_tpu.analysis import runtime as _sanitizer
            with _sanitizer.sanitize(
                    what="steady-state ServingEngine.step dispatch"):
                out = fn(*args)
            self.stats["sanitized_steps"] += 1
        else:
            out = fn(*args)
        d_nxt, self.kv_pool, d_pos, d_cnt = out[:4]
        chunk_outs = out[4:]
        # toks <- sampled ids; tables/seeds/scales are event-driven
        self._dev = (self._dev[0], d_pos, d_nxt, self._dev[3], d_cnt,
                     self._dev[5])
        t_s0 = time.perf_counter()
        dispatch_s = t_s0 - t_d0
        head_np, ctok_np, lanes_np, kvfull_np = self._fence_chunk_pulls(
            grp, g_kind, chunk_outs, [d_nxt if active else None])
        nxt = head_np[0]
        sync_s = time.perf_counter() - t_s0
        if active:
            self._decode_since_chunk += 1
            self.stats["steps"] += 1
            self.stats["decode_tokens"] += len(active)
            # per-slot dispatch accounting: dispatches_per_token =
            # decode_slot_dispatches / decode_tokens, 1.0 without
            # speculation — the speculative perf gate's denominator
            self.stats["decode_slot_dispatches"] += len(active)
            self.stats["idle_slot_steps"] += self.max_slots - len(active)
            r = self._metrics
            r.counter("serving.steps").inc()
            r.counter("serving.tokens_generated").inc(len(active))
            r.counter("serving.idle_slot_steps").inc(
                self.max_slots - len(active))
            if self.speculate is not None:
                # adaptive tick with every active slot at k=0: surface
                # the degraded tail width (the verify path never runs
                # here, so _spec_decode's gauge set cannot)
                r.gauge("serving.spec_k_effective").set(0)
            for i in active:
                s = self._slots[i]
                tok = int(nxt[i])
                s.tokens.append(tok)
                s.tok = tok
                s.pos += 1
                s.count += 1
                if self._history is not None:
                    # an adaptive spec engine on a plain (k=0) tick
                    # keeps the HOST history current; the device twin
                    # refreshes on the next event tick's dirty upload
                    self._history[i, min(s.pos,
                                         self.max_seq_len - 1)] = tok
                self._positions[i] = s.pos
                self._toks[i] = tok
                self._counts[i] = s.count
                eos = self.eos_token_id
                if eos is not None and tok == int(eos):
                    self._retire(i, "eos")
                elif s.count >= s.req.max_new_tokens:
                    self._retire(i, "length")
        if grp is not None:
            self._commit_chunk(grp, g_start, g_kind, ctok_np, lanes_np,
                               kvfull_np, dispatch_s + sync_s, tick_warm)
        return dispatch_s, sync_s

    def _spec_decode(self, active, steady, grp=None, tick_fn=None,
                     tick_warm=True, g_start=None, g_kind=None):
        """One speculative tick's decode: the (optional) draft round
        plus ONE batched verify dispatch — the fused tick program when
        a chunk is due (``grp``), carrying the front group's chunk in
        the same program — then the host commit of each slot's
        accepted prefix + corrected/bonus token. Returns
        (dispatch_s, sync_s) for the step-segment telemetry. Mirrors
        stay in lockstep with the device state for surviving slots; a
        retirement inside the commit loop marks the mirrors dirty like
        any other leave event."""
        from paddle_tpu import observability as obs

        ngram = self._history is not None
        K_eff = self._spec_k_eff
        verify_fn = tick_fn if grp is not None else self._verify_fns[K_eff]
        draft_fn = self._draft_fns.get(K_eff)
        t_d0 = time.perf_counter()

        def dispatch():
            if draft_fn is not None:
                props, self.draft_kv_pool = draft_fn(
                    self.draft_kv_pool, self._draft_dev, self._dev[1],
                    self._dev[2])
                nprop = self._nprop_full(K_eff)
            else:
                props, nprop = self._dev_prop
            args = (self.kv_pool,
                    *(grp.args() if grp is not None else ()),
                    *self._dev, props, nprop, self._dev_cap)
            if ngram:
                args += (self._dev_hist,)
            return props, nprop, verify_fn(*args)

        if self._sanitize and steady:
            from paddle_tpu.analysis import runtime as _sanitizer
            with _sanitizer.sanitize(
                    what="steady-state speculative ServingEngine.step "
                         "dispatch"):
                props_dev, nprop_dev, out = dispatch()
            self.stats["sanitized_steps"] += 1
        else:
            props_dev, nprop_dev, out = dispatch()
        if ngram:
            (g, acc, self.kv_pool, d_pos, d_tok, d_cnt, hist2, prop2,
             nprop2) = out[:9]
            chunk_outs = out[9:]
            self._dev_hist = hist2
            self._dev_prop = (prop2, nprop2)
        else:
            g, acc, self.kv_pool, d_pos, d_tok, d_cnt = out[:6]
            chunk_outs = out[6:]
        self._dev = (self._dev[0], d_pos, d_tok, self._dev[3], d_cnt,
                     self._dev[5])
        t_s0 = time.perf_counter()
        dispatch_s = t_s0 - t_d0
        head_np, ctok_np, lanes_np, kvfull_np = self._fence_chunk_pulls(
            grp, g_kind, chunk_outs, [g, acc, props_dev, nprop_dev])
        g_np, acc_np, prop_np, nprop_np = head_np
        sync_s = time.perf_counter() - t_s0

        self._decode_since_chunk += 1
        self.stats["steps"] += 1
        self.stats["spec_ticks"] += 1
        self.stats["decode_slot_dispatches"] += len(active)
        self.stats["idle_slot_steps"] += self.max_slots - len(active)
        r = self._metrics
        r.counter("serving.steps").inc()
        r.counter("serving.idle_slot_steps").inc(
            self.max_slots - len(active))
        pos_cap = self.max_seq_len - 1
        eos = self.eos_token_id
        committed_total = proposed_total = accepted_total = 0
        for i in active:
            s = self._slots[i]
            a = int(acc_np[i])
            # the EFFECTIVE proposal count — what the verify program
            # actually considered: min(raw nprop, per-slot adaptive
            # cap, tail width). Counting the raw draft nprop would
            # inflate spec_proposed/spec_rejected for capped slots and
            # bias the acceptance-rate telemetry low.
            proposed_total += min(int(nprop_np[i]),
                                  int(self._spec_cap[i]), K_eff)
            accepted_total += a
            r.histogram("serving.spec_accepted_len",
                        buckets=_SPEC_LEN_BUCKETS).observe(a)
            committed = ([int(t) for t in prop_np[i, :a]]
                         + [int(g_np[i, a])])
            for tok in committed:
                s.tokens.append(tok)
                s.tok = tok
                s.pos += 1
                s.count += 1
                committed_total += 1
                if ngram:
                    self._history[i, min(s.pos, pos_cap)] = tok
                self._positions[i] = s.pos
                self._toks[i] = tok
                self._counts[i] = s.count
                if eos is not None and tok == int(eos):
                    self._retire(i, "eos")
                    break
                if s.count >= s.req.max_new_tokens:
                    self._retire(i, "length")
                    break
        self.stats["decode_tokens"] += committed_total
        self.stats["spec_proposed"] += proposed_total
        self.stats["spec_accepted"] += accepted_total
        r.counter("serving.tokens_generated").inc(committed_total)
        r.counter("serving.spec_proposed").inc(proposed_total)
        r.counter("serving.spec_accepted").inc(accepted_total)
        r.counter("serving.spec_rejected").inc(
            proposed_total - accepted_total)
        if self.stats["spec_proposed"]:
            r.gauge("serving.spec_acceptance_rate").set(
                self.stats["spec_accepted"]
                / self.stats["spec_proposed"])
        r.gauge("serving.spec_k_effective").set(K_eff)
        self._ewma_spec_tokens.update(committed_total / len(active))
        self._tick_spec = (proposed_total, accepted_total)
        if self.speculate.adaptive:
            self._adapt_spec_k(active, acc_np, nprop_np)
            self._close_probe_window()
        tr = obs.active_tracer()
        if tr is not None:
            dur = dispatch_s + sync_s
            tr.record("serving.spec_verify", ts=time.time() - dur,
                      dur_s=dur, slots=len(active),
                      trace_ids=[self._slots[i].req.trace_id
                                 for i in active
                                 if self._slots[i] is not None],
                      proposed=proposed_total, accepted=accepted_total,
                      committed=committed_total)
        if grp is not None:
            self._commit_chunk(grp, g_start, g_kind, ctok_np, lanes_np,
                               kvfull_np, dispatch_s + sync_s, tick_warm)
        return dispatch_s, sync_s

    def _after_flight(self):
        """Post-event tail of a tick: flush any queued flight dump and
        refresh the gauges."""
        if self._dump_pending is not None:
            self.flight.auto_dump(self._dump_pending)
            self._dump_pending = None
        self._update_gauges()

    def _record_segments(self, admit_s, dispatch_s, sync_s):
        """Step-segment telemetry: cumulative stats + registry
        histograms. admit is observed every tick; prefill only on ticks
        that ran a wave, dispatch/sync only on ticks that decoded — so
        each histogram is the distribution of the segment when it
        actually happened, not diluted by structural zeros."""
        st = self.stats
        st["step_admit_s"] += admit_s
        st["step_prefill_s"] += self._tick_prefill_s
        r = self._metrics
        r.histogram("serving.step_admit_s").observe(admit_s)
        if self._tick_prefills:
            r.histogram("serving.step_prefill_s").observe(
                self._tick_prefill_s)
        if dispatch_s is not None:
            st["step_dispatch_s"] += dispatch_s
            st["step_sync_s"] += sync_s
            r.histogram("serving.step_dispatch_s").observe(dispatch_s)
            r.histogram("serving.step_sync_s").observe(sync_s)
            # capacity-estimator feed: the same decode-step cost the
            # histograms just observed (shed_infeasible prices deadlines
            # against this EWMA) — except fused CHUNK ticks, whose wall
            # is chunk-dominated (the chunk EWMAs in _commit_chunk own
            # those; feeding them here would inflate the decode-step
            # estimate and over-shed), and except the plain program's
            # FIRST dispatch, whose trace+compile would poison the
            # estimate for dozens of steps and shed feasible deadlines
            # right after startup. The two warm flags are distinct on
            # purpose: _step_fn_warm (the steady/sanitize gate) flips
            # on ANY first dispatch, including a fused chunk tick —
            # the plain step program may not have compiled yet.
            self._step_fn_warm = True
            if not self._tick_chunks:
                if self._ewma_step_warm:
                    self._ewma_step.update(dispatch_s + sync_s)
                else:
                    self._ewma_step_warm = True

    def _record_flight(self, admit_s, dispatch_s, sync_s, err=None):
        """One compact JSON-ready event per tick into the flight ring."""
        evt = {"step": self._step_seq, "ts": round(time.time(), 6),
               "ts_mono": round(time.perf_counter(), 6),
               "active": self.active_slots, "queued": len(self._queue),
               "blocks_used": self.pool.used_blocks,
               "blocks_reserved": self._reserved,
               "admitted": list(self._tick_admitted),
               "retired": [[rid, fin] for rid, fin in self._tick_retired],
               "preempted": list(self._tick_preempted),
               "resumed": list(self._tick_resumed),
               "swapped_out": list(self._tick_swapped_out),
               "swapped_in": list(self._tick_swapped_in),
               "host_blocks_used": (self.host_store.used_blocks
                                    if self.host_store is not None
                                    else None),
               "shed": [[rid, reason] for rid, reason in self._tick_shed],
               "prefills": [[R, s_pad, n]
                            for R, s_pad, n in self._tick_prefills],
               "chunk_tokens": self.chunk_tokens,
               "prefill_chunks": min(len(self._tick_chunks), 1),
               "chunk_rows": len(self._tick_chunks),
               "chunks": [[rid, st, nt]
                          for rid, st, nt in self._tick_chunks],
               "spec_k": (self._spec_k if self.speculate is not None
                          else None),
               "spec_proposed": (None if self._tick_spec is None
                                 else self._tick_spec[0]),
               "spec_accepted": (None if self._tick_spec is None
                                 else self._tick_spec[1]),
               "t_admit_s": round(admit_s, 6),
               "t_prefill_s": round(self._tick_prefill_s, 6),
               "t_dispatch_s": (None if dispatch_s is None
                                else round(dispatch_s, 6)),
               "t_sync_s": (None if sync_s is None else round(sync_s, 6))}
        if err is not None:
            evt["err"] = err
        self.flight.record(evt)
        self._tick_shed = []    # drained into this tick's event
        self._step_seq += 1

    def pop_result(self, request_id: int) -> RequestResult:
        """Remove and return a finished request's result. ``results``
        retains every finished request until collected — a long-running
        server must pop (or periodically clear) results or host memory
        grows with every request ever served."""
        return self.results.pop(request_id)

    def drain(self, max_steps: Optional[int] = None) -> Dict[int,
                                                             RequestResult]:
        """Step until every submitted request has finished (or
        ``max_steps`` elapsed). Returns ``self.results``."""
        steps = 0
        while not self.idle:
            # stall probe: a step that BEGINS with every slot free runs
            # _admit with the whole pool reclaimable (prefix cache
            # already squeezed via evict_free) and nothing in flight to
            # retire — if it still admits nothing, no future step can,
            # and looping would spin forever (e.g. an int8-pool request
            # whose worst case exceeds the whole pool — submit's
            # never-fits check is deliberately optimistic about prefix
            # sharing). A step that merely ENDS idle is not a stall: its
            # retirements feed the next step's admission.
            q0 = len(self._queue) if self.active_slots == 0 else -1
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if q0 > 0 and self.active_slots == 0 and len(self._queue) == q0:
                head = self._queue.peek()
                self.flight.auto_dump("pool_exhausted:drain_stall")
                raise PoolExhausted(
                    f"drain stalled: request {head.request_id} "
                    f"({len(head.prompt)}+{head.max_new_tokens} tokens) "
                    f"cannot be admitted even with an idle engine")
        return self.results

    def generate(self, prompts: Sequence, **req_kwargs) -> List[np.ndarray]:
        """Batch convenience: submit every prompt, drain, return the
        ``prompt+tokens`` id rows in submission order."""
        # tpu-lint: allow(host-sync): API boundary — prompts are host ids
        ids = [self.submit(Request(np.asarray(p).reshape(-1), **req_kwargs))
               for p in prompts]
        self.drain()
        return [self.results[i].ids for i in ids]

    # ------------------------------------------------- lifecycle: close
    def close(self):
        """Release the engine's device and host memory: the KV pool and
        stacked-weight arrays, the device mirrors, the jitted programs,
        and the prefix cache's host copies. In-flight and queued
        requests are DROPPED — :meth:`save_snapshot` first if they must
        survive. Idempotent; a closed engine rejects ``submit``/``step``
        with ``RuntimeError``. Long-running benches and tests should
        close (or use the engine as a context manager) so back-to-back
        engines don't stack live KV pools."""
        if self._closed:
            return
        self._closed = True
        for a in (self.kv_pool, self._stacked, self.draft_kv_pool,
                  getattr(self, "_draft_stacked", None)):
            try:
                if a is not None:
                    jax.tree_util.tree_map(
                        lambda x: x.delete() if hasattr(x, "delete")
                        else None, a)
            except Exception:   # noqa: BLE001 — best-effort release
                pass
        self.kv_pool = None
        self._stacked = None
        self._dev = None
        self._step_fn = None
        self.draft_kv_pool = None
        self._draft_stacked = None
        self._draft_dev = None
        self._verify_fns = {}
        self._draft_fns = {}
        self._prop_zeros = {}
        self._nprop_fulls = {}
        self._dev_hist = None
        self._dev_prop = None
        self._dev_cap = None
        self._jit_cache.clear()
        self._swap_fns = {}
        self._parked = {}
        self._staged = {}
        if self.host_store is not None:
            self.host_store.clear()
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
        self._slots = [None] * self.max_slots
        self._queue = _PriorityQueue()
        self._prefill_fifo = []
        self._tables = self._positions = self._toks = None
        self._seeds = self._counts = self._kv_scales = None

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -------------------------------------- crash-recoverable snapshot
    def snapshot(self) -> Dict:
        """Serializable engine state (``paddle_tpu.engine_snapshot/v1``):
        the queue and every active slot as resumable requests (id,
        prompt, generated-so-far tokens, seed, priority, remaining
        deadline), finished results, the prefix-cache keys, and the
        constructor config + a model fingerprint. Token-exact by
        construction: a request's tokens and RNG seed are the COMPLETE
        decode state — :meth:`restore` re-prefills the prompt, replays
        the generated tokens through the decode program and continues
        the same ``fold_in(seed, count)`` stream, so KV never needs to
        survive the crash.

        Call between ``step()`` calls, or after a ``step()`` that died
        on a fault — the host-side scheduler state stays consistent
        across an aborted tick (the fault sites fire *before* queue
        pops / token appends)."""
        now = time.perf_counter()

        def _req(req: Request, tokens, deadline_at=None):
            if deadline_at is not None:
                rem = max(deadline_at - now, 1e-9)
            elif req.deadline_s is not None and req._t_submit is not None:
                rem = max(req._t_submit + req.deadline_s - now, 1e-9)
            else:
                rem = req.deadline_s
            return {"request_id": req.request_id,
                    "prompt": [int(t) for t in req.prompt],
                    "max_new_tokens": req.max_new_tokens,
                    "seed": int(req.seed) if req.seed is not None else None,
                    "priority": req.priority, "seq": req._seq,
                    "trace_id": req.trace_id,
                    "deadline_remaining_s": rem,
                    "tokens": [int(t) for t in tokens]}

        # a slot still mid-prefill (chunked engines) has sampled no
        # tokens: serialize the resume state it was ADMITTED with (a
        # preempted request's generated-so-far tokens must survive a
        # crash that lands mid-re-prefill), plus the chunk cursor so a
        # postmortem can see how far its prefill got — restore
        # re-prefills from the tokens, so the cursor itself is
        # informational (KV never survives a crash by design)
        slots = []
        for s in self._slots:
            if s is None:
                continue
            d = _req(s.req,
                     (s.resume or []) if s.prefilling else s.tokens,
                     s.deadline_at)
            d["chunk_filled"] = int(s.filled) if s.prefilling else None
            slots.append(d)
        queue = [_req(r, r._resume_tokens or []) for r in self._queue]
        results = [{"request_id": res.request_id,
                    "prompt": [int(t) for t in res.prompt],
                    "tokens": [int(t) for t in res.tokens],
                    "gen_len": res.gen_len, "finish": res.finish,
                    "ttft_s": res.ttft_s, "tpot_s": res.tpot_s,
                    "trace_id": res.trace_id,
                    "prefix_hit_blocks": res.prefix_hit_blocks}
                   for res in self.results.values()]
        config = {"max_slots": self.max_slots,
                  "block_tokens": self.block_tokens,
                  "num_blocks": self.pool.num_blocks,
                  "max_seq_len": self.max_seq_len,
                  "cache_dtype": jnp.dtype(self.cache_dtype).name,
                  "temperature": self.temperature, "top_k": self.top_k,
                  "top_p": self.top_p,
                  "eos_token_id": self.eos_token_id, "seed": self.seed,
                  "prefix_caching": self.prefix_cache is not None,
                  "prefix_cache_blocks": (
                      self.prefix_cache.capacity
                      if self.prefix_cache is not None else 256),
                  "flight_capacity": self.flight.capacity,
                  "flight_dump_path": self.flight.auto_dump_path,
                  "max_queue": self.max_queue,
                  "shed_infeasible": self.shed_infeasible,
                  "chunk_tokens": self.chunk_tokens,
                  "decode_per_chunk": self.decode_per_chunk,
                  "chunk_autotune": self.chunk_autotune,
                  "slo_tpot_s": self.slo_tpot_s,
                  "speculate": (self.speculate.to_config()
                                if self.speculate is not None else None),
                  "offload": self.offload,
                  "host_pool_blocks": (self.host_store.capacity
                                       if self.host_store is not None
                                       else None),
                  "offload_prefetch": self.offload_prefetch,
                  "sanitize": self._sanitize_mode}
        fingerprint = {"arch": self.arch, "num_layers": self._num_layers,
                       "dkv": self._dkv}
        return {"schema": ENGINE_SNAPSHOT_SCHEMA, "ts": time.time(),
                "step_seq": self._step_seq, "config": config,
                "model": fingerprint, "slots": slots, "queue": queue,
                "results": results,
                "prefix_keys": (self.prefix_cache.keys()
                                if self.prefix_cache is not None else []),
                "seeds_issued": self._seeds_issued,
                "submit_seq": self._submit_seq}

    def save_snapshot(self, root: str) -> str:
        """Commit :meth:`snapshot` to disk through the PR 4 integrity
        path: ``<root>/step_<seq>/engine.json`` (atomic tmp+rename),
        then the ``<root>/integrity/step_<seq>.json`` manifest whose
        existence IS the commit marker — :meth:`restore` walks back
        past uncommitted or corrupt snapshots exactly like checkpoint
        resume does. Returns the step directory."""
        from paddle_tpu.resilience import faults as _faults
        from paddle_tpu.resilience import integrity as _integ

        fault = _faults.maybe_fire("serving.snapshot")
        snap = self.snapshot()
        if self._sanitize_roundtrip:
            # sanitize="roundtrip"/"all": verify the snapshot being
            # committed restores byte-identically (canonical form)
            # BEFORE trusting it — SnapshotDriftError beats silently
            # persisting a snapshot that loses state. The check builds
            # a full twin engine (second KV pool!); if it CANNOT run —
            # e.g. no allocator headroom on a crash path — commit
            # unverified with a warning rather than abort the very
            # snapshot meant to preserve state: only genuine drift is
            # worth refusing to persist.
            from paddle_tpu.analysis import runtime as _sanitizer
            try:
                _sanitizer.snapshot_roundtrip(self, snap=snap)
            except _sanitizer.SnapshotDriftError:
                raise
            except Exception:   # noqa: BLE001 — check unavailable
                logger.warning(
                    "snapshot roundtrip check could not run; "
                    "committing the snapshot UNVERIFIED", exc_info=True)
        step = snap["step_seq"]
        step_dir = os.path.join(root, f"step_{step}")
        os.makedirs(step_dir, exist_ok=True)
        path = os.path.join(step_dir, "engine.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if fault is not None and fault.kind == "hang":
            # the TORN window, held open on demand: engine.json is
            # committed but the manifest (the commit marker) is not.
            # A SIGKILL landing here leaves exactly the half-commit
            # that load_snapshot's walk-back must skip — the
            # cross-process torn-snapshot test kills the worker inside
            # this sleep and pins the walk-back.
            time.sleep(float(fault.payload.get("seconds", 3600.0)))
        _integ.write_manifest(root, step, _integ.file_checksums(step_dir))
        self._metrics.counter("serving.snapshots").inc()
        return step_dir

    @staticmethod
    def load_snapshot(root: str) -> Dict:
        """Newest committed-and-intact snapshot under ``root``: walk the
        manifest steps newest-first, skip any whose files fail the
        size/crc check (``resilience.snapshot_corrupt_skipped``) — one
        torn snapshot write must not strand the restore."""
        from paddle_tpu.resilience import integrity as _integ
        from paddle_tpu.resilience import record_event

        for step in _integ.manifest_steps(root):
            manifest = _integ.read_manifest(root, step)
            if manifest is None:
                continue
            step_dir = os.path.join(root, f"step_{step}")
            ok, reason = _integ.verify_files(manifest, step_dir)
            if not ok:
                record_event("snapshot_corrupt_skipped")
                logger.warning("engine snapshot step %d failed "
                               "verification (%s); walking back",
                               step, reason)
                continue
            with open(os.path.join(step_dir, "engine.json")) as f:
                return json.load(f)
        raise FileNotFoundError(
            f"no committed intact engine snapshot under {root}")

    @classmethod
    def restore(cls, model, source, *, state: Optional[Dict] = None,
                **overrides) -> "ServingEngine":
        """Rebuild an engine from a snapshot (dict, or a
        :meth:`save_snapshot` root directory) and re-admit EVERY
        request — in-flight slots and queued work alike — through the
        token-exact resume path: zero loss across a crash. Finished
        results carry over. ``overrides`` replace constructor config
        (e.g. a new ``flight_dump_path``). Snapshots are MESH-FREE
        (host-canonical: KV never serializes, scales/tokens are
        host-side canonical forms), so ``mesh=``/``layout=`` overrides
        restore the same snapshot onto any mesh shape — including a
        single chip — byte-identically (tests/test_serving_mp.py)."""
        from paddle_tpu.resilience import record_event

        snap = (cls.load_snapshot(source) if isinstance(source, str)
                else source)
        if snap.get("schema") != ENGINE_SNAPSHOT_SCHEMA:
            raise RestoreError(
                "schema",
                f"not an engine snapshot: schema "
                f"{snap.get('schema')!r} != {ENGINE_SNAPSHOT_SCHEMA!r}")
        cfg = dict(snap["config"])
        cfg["cache_dtype"] = jnp.dtype(cfg["cache_dtype"])
        spec_cfg = cfg.get("speculate")
        if isinstance(spec_cfg, dict) and "speculate" not in overrides:
            if spec_cfg.get("proposer") == "draft":
                raise RestoreError(
                    "draft_model_missing",
                    "snapshot used the draft-model proposer; models "
                    "don't serialize — pass speculate=SpecConfig(..., "
                    "draft_model=...) as a restore override (or "
                    "speculate=None to restore without speculation)")
            cfg["speculate"] = SpecConfig(**spec_cfg)
        cfg.update(overrides)
        eng = cls(model, state=state, **cfg)
        fp = snap.get("model", {})
        if fp and (fp.get("arch") != eng.arch
                   or fp.get("num_layers") != eng._num_layers
                   or fp.get("dkv") != eng._dkv):
            eng.close()     # the mismatched engine must not leak its pool
            raise RestoreError(
                "model_fingerprint",
                f"model mismatch: snapshot was taken on "
                f"{fp}, restoring onto arch={eng.arch} "
                f"L={eng._num_layers} dkv={eng._dkv}")
        eng._seeds_issued = int(snap.get("seeds_issued", 0))
        eng._submit_seq = int(snap.get("submit_seq", 0))
        now = time.perf_counter()
        # in-flight slots first, then the queue — both were serialized
        # in scheduling order and keep their original seq, so the
        # restored queue pops in the order the crashed engine would have
        restored = []
        for rs in snap["slots"] + snap["queue"]:
            # tpu-lint: allow(host-sync): snapshot JSON is host data
            req = Request(np.asarray(rs["prompt"], np.int32),
                          rs["max_new_tokens"], seed=rs["seed"],
                          deadline_s=rs["deadline_remaining_s"],
                          priority=rs.get("priority", "normal"),
                          request_id=rs["request_id"],
                          trace_id=rs.get("trace_id"))
            req._seq = int(rs.get("seq", 0))
            eng._submit_seq = max(eng._submit_seq, req._seq + 1)
            req._t_submit = now     # remaining deadline re-anchors here
            req._resume_tokens = list(rs["tokens"]) or None
            eng._queue.push(req)
            restored.append(req.request_id)
        for rr in snap.get("results", []):
            # tpu-lint: allow(journal-coverage): reconstructs results a
            # terminal transition already produced (and, router-side,
            # already journaled) — not a new transition
            # tpu-lint: allow(host-sync): snapshot JSON is host data
            eng.results[rr["request_id"]] = RequestResult(
                rr["request_id"], np.asarray(rr["prompt"], np.int32),
                rr["tokens"], rr["gen_len"], rr["finish"], rr["ttft_s"],
                rr["tpot_s"], rr["prefix_hit_blocks"],
                trace_id=rr.get("trace_id"))
        eng._step_seq = int(snap.get("step_seq", 0)) + 1
        eng._metrics.counter("serving.restores").inc()
        record_event("engine_restored")
        eng.flight.mark("restore", restored=restored,
                        results_carried=len(snap.get("results", [])),
                        from_step_seq=snap.get("step_seq"))
        eng.flight.auto_dump("restore")
        eng._update_gauges()
        return eng
