"""Replicated serving tier: N engine replicas behind one fault-tolerant
router (docs/SERVING.md §Replicated tier).

One ``ServingEngine`` is overload-safe and crash-recoverable (PR 8),
but it is still ONE engine: a dead engine takes its queue and in-flight
slots with it, and nothing notices. This module is the data-parallel
tier on top — the serving analog of the reference's Fleet/elastic layer
(ElasticManager heartbeats + coordination-service membership, PAPER.md):
a :class:`Router` owns N in-process ``ServingEngine`` replicas behind
one submit/step/drain surface and keeps the tier serving through
replica death, drain and growth.

Three mechanisms:

* **Placement** — prefix-affinity first: the block-aligned prompt
  prefix is content-hashed (the same full-block rule the
  ``PrefixCache`` keys by) and routed to a stable replica slot, so
  repeat prefixes land where their KV blocks already live. Ties (no
  full prefix block) and overloaded affinity targets fall back to
  least-loaded, ordered by each replica's public
  ``estimated_ttft_s(request, default=0.0)`` (cold = maximally
  available, the documented convention) and its
  ``serving.pool_blocks_*`` occupancy. A replica that sheds
  (``Rejected``) just means "try the next one"; only when EVERY
  placeable replica sheds does the router raise
  ``Rejected(reason="tier_saturated")`` — tier-level typed shedding.
* **Health + zero-loss failover** — every router tick heartbeats each
  live replica through the ``router.heartbeat`` fault site
  (``resilience.faults.KNOWN_SITES``): a raising fault IS a missed
  heartbeat, and consecutive misses drive the per-replica state
  machine healthy → suspect → dead (a closed engine, or an exception
  out of ``replica.step()``, is declared dead immediately). A dead
  replica is rebuilt zero-loss: restore from its last
  ``save_snapshot()`` if the integrity manifest verifies, else
  RE-PLACE every journaled accepted request — with its
  generated-so-far tokens through the PR 8 token-exact resume path
  (``ServingEngine.admit_resumable``) — onto surviving replicas.
  Either way the final tokens are bit-identical to an unfailed run:
  resume continues each request's own ``fold_in(seed, count)`` stream,
  and a from-scratch re-run is the same pure function of
  (prompt, seed, sampling config).
* **Elastic drain / growth** — :meth:`Router.drain_replica` stops
  placement to a replica, snapshots it, migrates its in-flight and
  queued work onto the survivors (same resume path) and removes it;
  :meth:`Router.add_replica` joins a new replica warm (its prefill +
  step programs compiled before it takes traffic). The tier scales
  under load without dropping a request.

**The durable request journal.** With a ``root`` directory configured
the router appends every accept / placement / progress / finish to an
append-only CRC-framed JSONL journal (``paddle_tpu.router_journal/v1``)
through the shared ``RetryPolicy``, and snapshots replicas round-robin
every ``snapshot_every`` ticks through the PR 4 integrity-manifest
commit path. Replica death is survived from the in-memory mirror of
that journal; a whole-ROUTER crash is survived by
:meth:`Router.recover`, which replays the journal (skipping corrupt
lines — ``resilience.journal_corrupt_skipped``), restores every replica
whose snapshot verifies and re-places the rest. ``root=None`` runs the
tier memory-only: replica failover still loses nothing (the router
process is alive), only router-process durability is waived.
"""

import hashlib
import logging
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.serving.engine import (DrainTimeout, Rejected, Request,
                                       RequestResult, RestoreError,
                                       ServingEngine)
from paddle_tpu.serving.journal import (ROUTER_JOURNAL_SCHEMA,
                                        RouterJournal)
from paddle_tpu.serving.pool import (PoolExhausted, TierPrefixStore,
                                     chain_keys)

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["Router", "RouterJournal", "ROUTER_JOURNAL_SCHEMA",
           "REPLICA_STATES", "REPLICA_ROLES", "ReplicaRole"]

#: replica health states. healthy/suspect take placements (suspect only
#: when no healthy replica can), draining serves but takes none, dead is
#: awaiting failover, removed is a retired slot (kept so prefix-affinity
#: hashing stays stable as the tier grows).
REPLICA_STATES = ("healthy", "suspect", "dead", "draining", "removed")
_STATE_RANK = {s: i for i, s in enumerate(REPLICA_STATES)}


class ReplicaRole:
    """Splitwise/DistServe-style role disaggregation: a ``prefill``
    replica takes fresh admissions and releases each request at first
    token; a ``decode`` replica takes the migrated resume work;
    ``mixed`` (the default) does both. Placement filters candidates by
    the request's phase and FALLS BACK to any placeable replica rather
    than strand work — roles are a routing preference, never a
    correctness gate (migration rides the token-exact resume path, so
    a roled run is bit-identical to a mixed one)."""

    PREFILL = "prefill"
    DECODE = "decode"
    MIXED = "mixed"


REPLICA_ROLES = (ReplicaRole.PREFILL, ReplicaRole.DECODE,
                 ReplicaRole.MIXED)


class _Tracked:
    """Router-side mirror of one accepted request — everything needed
    to re-place it token-exactly if its replica dies."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "seed", "priority",
                 "deadline_s", "t_accept", "replica", "tokens",
                 "finished", "journaled_tokens", "trace_id")

    def __init__(self, rid, prompt, max_new_tokens, seed, priority,
                 deadline_s, replica, trace_id=None):
        self.rid = rid
        self.trace_id = trace_id        # causal chain key, accept-minted
        self.prompt = prompt            # np.int32 host ids
        self.max_new_tokens = max_new_tokens
        self.seed = seed
        self.priority = priority
        self.deadline_s = deadline_s
        self.t_accept = time.perf_counter()
        self.replica = replica
        self.tokens: List[int] = []     # last observed generated prefix
        self.finished = False
        self.journaled_tokens = 0       # progress length last journaled

    def remaining_deadline(self) -> Optional[float]:
        if self.deadline_s is None:
            return None
        return max(self.deadline_s
                   - (time.perf_counter() - self.t_accept), 1e-9)

    def as_request(self) -> Request:
        # trace_id rides along: a failover/drain re-placement is the
        # SAME causal request — its chain must not fork at migration
        return Request(self.prompt, self.max_new_tokens, seed=self.seed,
                       deadline_s=self.remaining_deadline(),
                       priority=self.priority, request_id=self.rid,
                       trace_id=self.trace_id)


class _Replica:
    __slots__ = ("engine", "state", "misses", "root", "role")

    def __init__(self, engine, root, role: str = ReplicaRole.MIXED):
        self.engine = engine
        self.state = "healthy"
        self.misses = 0
        self.root = root
        self.role = role


class Router:
    """N in-process ``ServingEngine`` replicas behind one
    submit/step/drain surface (module docstring has the design).

    ``replicas`` engines are built from ``model`` + ``engine_kwargs``
    (every constructor knob ``ServingEngine`` takes), sharing one
    inference state dict so N replicas don't hold N weight copies.
    ``root`` arms durability: the request journal at
    ``<root>/journal.jsonl`` plus per-replica snapshot roots
    ``<root>/replica_<i>`` written round-robin every ``snapshot_every``
    ticks. ``suspect_after``/``dead_after`` are the consecutive
    missed-heartbeat thresholds of the health state machine;
    ``retry_policy`` (PR 4 ``RetryPolicy``) governs journal appends and
    snapshot commits. The router duck-types the engine's bench surface
    (``submit``/``step``/``drain``/``results``/``stats``/``idle``/
    ``close``), so the serving benches drive either interchangeably."""

    def __init__(self, model, *, replicas: int = 2, state=None,
                 root: Optional[str] = None,
                 suspect_after: int = 1, dead_after: int = 3,
                 snapshot_every: Optional[int] = 16,
                 journal_progress_every: int = 8,
                 retry_policy=None,
                 affinity_overload_factor: float = 4.0,
                 rebuild_dead: bool = True,
                 flight_capacity: int = 256,
                 flight_dump_path: Optional[str] = None,
                 watchdog=None,
                 processes: bool = False,
                 model_factory=None,
                 roles: Optional[Sequence[str]] = None,
                 rpc_timeout_s: float = 180.0,
                 heartbeat_timeout_s: float = 10.0,
                 start_timeout_s: float = 300.0,
                 tier_prefix_blocks: Optional[int] = 256,
                 seed: int = 0, **engine_kwargs):
        from paddle_tpu.inference import _inference_state
        from paddle_tpu.observability.flight import FlightRecorder
        from paddle_tpu.resilience.retry import RetryPolicy

        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if suspect_after < 1 or dead_after < suspect_after:
            raise ValueError(
                f"need 1 <= suspect_after <= dead_after, got "
                f"suspect_after={suspect_after} dead_after={dead_after}")
        self.processes = bool(processes)
        self.model_factory = model_factory
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.start_timeout_s = float(start_timeout_s)
        if self.processes:
            if model_factory is None:
                raise ValueError(
                    "processes=True requires model_factory= (a picklable "
                    "zero-arg callable; each worker builds its OWN model "
                    "— weights must be deterministic so replicas agree)")
            for k in ("mesh", "layout", "speculate"):
                if engine_kwargs.get(k) is not None:
                    raise ValueError(
                        f"processes=True does not support engine kwarg "
                        f"{k!r} yet — run mesh/speculative replicas "
                        f"in-process")
        elif model is None:
            raise ValueError("model is required for in-process replicas "
                             "(processes=False)")
        if roles is None:
            roles = [ReplicaRole.MIXED] * replicas
        roles = [str(r) for r in roles]
        if len(roles) != replicas:
            raise ValueError(f"roles must name one role per replica: "
                             f"got {len(roles)} for {replicas} replicas")
        for r in roles:
            if r not in REPLICA_ROLES:
                raise ValueError(f"unknown replica role {r!r}; one of "
                                 f"{REPLICA_ROLES}")
        self.model = model
        if state is not None:
            self._state = state
        else:
            # processes mode: the workers build their own models and
            # inference state; the parent never touches device weights
            self._state = (None if self.processes
                           else _inference_state(model))
        # tpu-lint: volatile(constructor config — recover() rebuilds it
        # from router_kwargs; set_overload_controls re-arms post-bench)
        self._engine_kwargs = dict(engine_kwargs)
        # one postmortem file for the whole tier: replica engines
        # inherit the router's dump path unless given their own, so
        # engine-level preempt/shed/restore markers land beside the
        # router's failover/kill markers
        if flight_dump_path is not None \
                and "flight_dump_path" not in self._engine_kwargs:
            self._engine_kwargs["flight_dump_path"] = flight_dump_path
        self.seed = int(seed)
        self._seeds_issued = 0
        self.root = root
        self.suspect_after = int(suspect_after)
        self.dead_after = int(dead_after)
        self.snapshot_every = (int(snapshot_every)
                               if snapshot_every else None)
        self.journal_progress_every = max(int(journal_progress_every), 1)
        self.retry_policy = retry_policy or RetryPolicy()
        self.affinity_overload_factor = float(affinity_overload_factor)
        self.rebuild_dead = bool(rebuild_dead)
        self.journal = (RouterJournal(os.path.join(root, "journal.jsonl"),
                                      self.retry_policy)
                        if root is not None else None)
        self._replicas: List[_Replica] = []
        for i in range(replicas):
            self._replicas.append(
                _Replica(self._new_engine(i), self._replica_root(i),
                         role=roles[i]))
        self._requests: Dict[int, _Tracked] = {}
        self._open: set = set()         # accepted, not yet finished
        self.results: Dict[int, RequestResult] = {}
        # tpu-lint: volatile(recover() rebuilds orphans through
        # _queue_replace from the journal fold)
        self._pending_replace: List[_Tracked] = []
        # tpu-lint: volatile(journal/snapshot cadence counter)
        self._tick = 0
        # tpu-lint: volatile(round-robin snapshot cursor)
        self._snap_cursor = 0
        self._closed = False
        self.flight = FlightRecorder(capacity=flight_capacity,
                                     auto_dump_path=flight_dump_path,
                                     name="serving-router")
        # SLO burn-rate watchdog (observability.slo.BurnRateWatchdog):
        # checked on its own tick cadence; a trip dumps flight rings +
        # a timeline slice (docs/OBSERVABILITY.md §Burn-rate watchdog)
        self.watchdog = watchdog
        # tpu-lint: volatile(tier telemetry; the registry counters are
        # the cross-recovery accounting)
        self.router_stats = dict(
            placed=0, rejected_tier=0, heartbeat_misses=0,
            replica_deaths=0, failovers=0, replaced=0, drains=0,
            replica_kills=0, snapshots=0, prefix_shared_blocks=0)
        # tpu-lint: volatile(absorbed stats of retired engines —
        # telemetry, not protocol state)
        self._stats_base: Dict[str, float] = {}
        # tpu-lint: volatile(absorbed prefix hit/lookup counters of
        # retired engines — telemetry, not protocol state)
        self._prefix_base = [0, 0]
        # the tier-wide prefix index + host payload cache
        # (docs/SERVING.md §Hierarchical KV). Losing it costs only
        # future block copies — it is rebuilt organically from
        # placements, so it lives outside the journal/snapshot protocol.
        # tpu-lint: volatile(hint index + host cache; recover() and
        # failover repopulate it from live placements)
        self._tier_prefix = (TierPrefixStore(int(tier_prefix_blocks))
                             if tier_prefix_blocks else None)
        # tpu-lint: volatile(rids mid role-migration this tick — picks
        # the journal kind for the block share at re-placement)
        self._migrating: set = set()
        if self.journal is not None:
            self.journal.append("header", schema=ROUTER_JOURNAL_SCHEMA,
                                replicas=replicas, seed=self.seed)
        self._update_gauges()

    # ------------------------------------------------------------ plumbing
    def _replica_root(self, i: int) -> Optional[str]:
        return (os.path.join(self.root, f"replica_{i}")
                if self.root is not None else None)

    def _new_engine(self, i: int, restore_root: Optional[str] = None):
        """Build replica ``i``'s engine. Every replica's metric series
        carry a ``replica="<i>"`` label (a registry view — storage
        stays process-global), so :meth:`metrics_snapshot` can merge
        the tier and a dashboard can still tell replicas apart.
        ``processes=True`` spawns a worker process behind a
        :class:`~paddle_tpu.serving.worker.ReplicaProxy` instead —
        with ``restore_root`` the WORKER attempts the snapshot restore
        itself and reports restored/covered in its handshake."""
        if self.processes:
            from paddle_tpu.serving.worker import ReplicaProxy
            return ReplicaProxy.start(
                self.model_factory, engine_kwargs=self._engine_kwargs,
                replica=i, seed=self.seed, restore_root=restore_root,
                rpc_timeout_s=self.rpc_timeout_s,
                start_timeout_s=self.start_timeout_s,
                retry_policy=self.retry_policy)
        return ServingEngine(self.model, state=self._state,
                             seed=self.seed,
                             metrics_labels={"replica": str(i)},
                             **self._engine_kwargs)

    def _restore_overrides(self, i: int) -> Dict:
        """Overrides every replica restore needs: the live SpecConfig
        (draft models don't serialize — without this a draft-proposer
        tier could never take the restore path; restore would raise
        ``RestoreError("draft_model_missing")`` and every failover
        would silently degrade to redistribution), and the live
        mesh/layout (snapshots are mesh-free, so a sharded router's
        restored replica must be re-handed its mesh explicitly or it
        would come back single-device) — plus the replica metric label,
        which is a live-construction knob snapshots never carry."""
        out = {"metrics_labels": {"replica": str(i)}}
        for key in ("speculate", "mesh", "layout"):
            v = self._engine_kwargs.get(key)
            if v is not None:
                out[key] = v
        return out

    @property
    def num_replicas(self) -> int:
        """Replica SLOTS (incl. removed) — the stable affinity modulus."""
        return len(self._replicas)

    @property
    def live_replicas(self) -> List[int]:
        return [i for i, r in enumerate(self._replicas)
                if r.state in ("healthy", "suspect", "draining")
                and r.engine is not None and not r.engine.closed]

    def health(self) -> List[str]:
        """Per-slot health states, index-aligned with the replicas."""
        return [r.state for r in self._replicas]

    def replica_engine(self, i: int) -> Optional[ServingEngine]:
        return self._replicas[i].engine

    def replica_snapshot_root(self, i: int) -> Optional[str]:
        return self._replicas[i].root

    @property
    def temperature(self) -> float:
        return float(self._engine_kwargs.get("temperature", 0.0))

    def _update_gauges(self):
        from paddle_tpu.observability import registry
        r = registry()
        r.gauge("serving.router.replicas_live").set(
            len(self.live_replicas))
        # tier-merged prefix reuse: every replica's counters (incl.
        # retired engines' absorbed base) folded into ONE rate, plus
        # the cross-replica share rate of the tier store — the numbers
        # router-mode benches report (per-replica rates alone hid the
        # tier-level reuse picture)
        r.gauge("serving.router.prefix_hit_rate").set(
            self.prefix_hit_rate)
        if self._tier_prefix is not None:
            r.gauge("serving.router.tier_prefix_hit_rate").set(
                self._tier_prefix.hit_rate)
        for i, rep in enumerate(self._replicas):
            r.gauge("serving.router.replica_state",
                    replica=str(i)).set(_STATE_RANK[rep.state])

    # ----------------------------------------------------------- placement
    def _affinity_slot(self, prompt) -> Optional[int]:
        """Stable replica slot for a prompt's block-aligned prefix, or
        None when the prompt has no full block to share (the same
        ``(P-1)//block_tokens`` rule the ``PrefixCache`` caps lookups
        at, so affinity exists exactly when there is cacheable KV)."""
        live = self.live_replicas
        if not live:
            return None
        bt = self._replicas[live[0]].engine.block_tokens
        n_full = (len(prompt) - 1) // bt
        if n_full == 0:
            return None
        # tpu-lint: allow(host-sync): hashing host token ids (never device)
        digest = hashlib.blake2b(
            np.ascontiguousarray(prompt[:n_full * bt],
                                 dtype=np.int64).tobytes(),
            digest_size=8).digest()
        return int.from_bytes(digest, "big") % self.num_replicas

    def _placeable(self, phase: Optional[str] = None) -> List[int]:
        """Replica indices that take new placements: healthy first;
        suspect only when no healthy replica exists (a suspect replica
        is probably alive — better than shedding the tier). When
        ``phase`` is given ("prefill" / "decode"), replicas whose role
        matches it (or is mixed) are PREFERRED — but a role mismatch
        never strands work: if no role-compatible replica is placeable
        the full candidate set is returned."""
        healthy = [i for i, r in enumerate(self._replicas)
                   if r.state == "healthy" and r.engine is not None
                   and not r.engine.closed]
        base = healthy or [i for i, r in enumerate(self._replicas)
                           if r.state == "suspect" and r.engine is not None
                           and not r.engine.closed]
        if phase is None:
            return base
        pref = [i for i in base
                if self._replicas[i].role in (phase, ReplicaRole.MIXED)]
        return pref or base

    def _placement_order(self, request: Request,
                         phase: Optional[str] = None):
        """(ordered candidate indices, policy): the affinity slot first
        unless its load exceeds ``affinity_overload_factor`` x the
        least-loaded candidate, then the rest by ascending load score
        — ``estimated_ttft_s(request, default=0.0)`` (cold = maximally
        available) tie-broken by pool-block occupancy and queue
        depth, the same signals the ``serving.pool_blocks_*`` /
        ``serving.queue_depth`` gauges export."""
        cands = self._placeable(phase)
        if not cands:
            return [], "none"
        loads = {}
        for i in cands:
            eng = self._replicas[i].engine
            est = eng.estimated_ttft_s(request, default=0.0)
            pool_frac = eng.pool.used_blocks / max(
                eng.pool.num_blocks - 1, 1)
            loads[i] = (est, pool_frac, eng.queued)
        by_load = sorted(cands, key=lambda i: loads[i])
        aff = self._affinity_slot(request.prompt)
        if aff is None:
            return by_load, "least_loaded"
        # linear probe from the stable slot to the first candidate, so
        # affinity survives membership churn (a dead slot's prefixes
        # consistently land on its successor, not scattered)
        n = self.num_replicas
        aff = next(((aff + off) % n for off in range(n)
                    if (aff + off) % n in loads), None)
        if aff is None:
            return by_load, "least_loaded"
        la, lmin = loads[aff][0], loads[by_load[0]][0]
        if la > self.affinity_overload_factor * (lmin + 1e-3):
            # the affinity target is drowning while someone else is
            # near-idle: prefix reuse is not worth the queueing delay
            # (the tier prefix store then turns the lost affinity into
            # a block copy instead of a recompute — _share_prefix)
            return by_load, "least_loaded"
        return ([aff] + [i for i in by_load if i != aff]), "affinity"

    def _share_prefix(self, idx: int, prompt, *, rid=None,
                      event: str = "prefix_share") -> int:
        """Stage finished prefill blocks from the tier onto replica
        ``idx`` ahead of a placement (docs/SERVING.md §Hierarchical
        KV): the prompt's block-aligned chain keys are probed against
        the :class:`TierPrefixStore`; the leading run replica ``idx``
        lacks but a sibling (or the store's host cache) can supply is
        fetched — in-process via ``export_prefix_blocks``, cross-
        process via the ``block_fetch`` RPC — cached host-side, and
        delivered via ``import_prefix_blocks`` so the admission-time
        prefix lookup hits blocks prefilled on ANOTHER replica.
        Best-effort by construction: an evicted entry, a dead owner or
        a full pool just shortens the copied run (and trims the hint);
        the placement itself never depends on the share."""
        from paddle_tpu.observability import registry

        store = self._tier_prefix
        eng = self._replicas[idx].engine
        if store is None or eng is None or eng.closed \
                or not hasattr(eng, "import_prefix_blocks"):
            return 0
        bt = eng.block_tokens
        n_full = (len(prompt) - 1) // bt    # the PrefixCache lookup cap
        if n_full <= 0:
            return 0
        # tpu-lint: allow(host-sync): prompts are host token ids
        keys = chain_keys(np.asarray(prompt)[:n_full * bt], bt)
        store.lookup_blocks += len(keys)
        missing = store.missing_run(keys, idx)
        # the placed request prefills (or copy-adopts) these blocks on
        # idx either way — record the hint AFTER the missing-run probe
        store.note_owner(keys, idx)
        if not missing:
            return 0
        payloads: Dict[str, tuple] = {}
        fetch: List[str] = []
        for k in missing:
            hit = store.cached(k)
            if hit is not None:
                payloads[k] = hit
            else:
                fetch.append(k)
        if fetch:
            by_owner: Dict[int, List[str]] = {}
            for k in fetch:
                o = store.owner_of(k, exclude=idx)
                if o is not None:
                    by_owner.setdefault(o, []).append(k)
            for o, ks in sorted(by_owner.items()):
                src = self._replicas[o].engine
                if src is None or src.closed \
                        or not hasattr(src, "export_prefix_blocks"):
                    continue
                try:
                    out = src.export_prefix_blocks(ks)
                except Exception:   # noqa: BLE001 — best-effort fetch
                    logger.warning("router: tier prefix fetch from "
                                   "replica %d failed", o, exc_info=True)
                    continue
                for k, (depth, kv) in out.items():
                    store.put(k, depth, kv)
                    payloads[k] = (depth, kv)
                gone = [k for k in ks if k not in out]
                if gone:
                    # the owner evicted these — trim the stale hint
                    store.forget(gone, o)
        run: Dict[str, tuple] = {}
        for k in missing:
            if k not in payloads:
                break   # chain broken: a gapped copy is never hit
            run[k] = payloads[k]
        if not run:
            return 0
        try:
            imported = int(eng.import_prefix_blocks(run))
        except Exception:   # noqa: BLE001 — best-effort delivery
            logger.warning("router: tier prefix import into replica %d "
                           "failed", idx, exc_info=True)
            return 0
        if imported:
            store.hit_blocks += imported
            self.router_stats["prefix_shared_blocks"] = \
                self.router_stats.get("prefix_shared_blocks", 0) + imported
            registry().counter("serving.router.prefix_shared_blocks",
                               event=event).inc(imported)
            self.flight.mark(event, replica=idx, blocks=imported,
                             rid=rid)
            if self.journal is not None:
                if event == "migrate_blocks":
                    self.journal.append("migrate_blocks", rid=rid,
                                        replica=idx, blocks=imported)
                else:
                    self.journal.append("prefix_share", rid=rid,
                                        replica=idx, blocks=imported)
        return imported

    def submit(self, request) -> int:
        """Place a request on the tier (accepts a :class:`Request` or a
        1-D prompt) and return its id; the result lands in
        ``self.results``. Seeds are assigned by the ROUTER before
        placement, so a re-placed request reproduces its tokens
        bit-identically on any replica. Raises ``ValueError`` /
        ``PoolExhausted`` for structurally impossible requests (every
        config-identical replica would refuse) and
        ``Rejected(reason="tier_saturated")`` when every placeable
        replica sheds it."""
        from paddle_tpu.observability import registry

        if self._closed:
            raise RuntimeError("Router is closed")
        if not isinstance(request, Request):
            request = Request(request)
        if request.seed is None:
            request.seed = self.seed + self._seeds_issued
            self._seeds_issued += 1
        phase = ("decode" if getattr(request, "_resume_tokens", None)
                 else "prefill")
        order, policy = self._placement_order(request, phase)
        r = registry()
        if not order:
            self.router_stats["rejected_tier"] += 1
            r.counter("serving.router.rejected",
                      reason="tier_saturated").inc()
            raise Rejected("tier_saturated",
                           "no live replica can take placements")
        last_pool_exhausted = None
        n_pool_exhausted = 0
        for j, idx in enumerate(order):
            eng = self._replicas[idx].engine
            try:
                rid = eng.submit(request)
            except Rejected:
                continue
            except PoolExhausted as e:
                last_pool_exhausted = e
                n_pool_exhausted += 1
                continue
            t = _Tracked(rid, request.prompt, request.max_new_tokens,
                         request.seed, request.priority,
                         request.deadline_s, idx,
                         trace_id=request.trace_id)
            self._requests[rid] = t
            self._open.add(rid)
            self.router_stats["placed"] += 1
            r.counter("serving.router.placed",
                      policy=policy if j == 0 else "least_loaded").inc()
            if self.journal is not None:
                self.journal.append(
                    "accept", rid=rid, trace_id=request.trace_id,
                    prompt=[int(x) for x in request.prompt],
                    max_new_tokens=request.max_new_tokens,
                    seed=request.seed, priority=request.priority,
                    deadline_s=request.deadline_s, replica=idx)
            try:
                self._share_prefix(idx, request.prompt, rid=rid)
            except Exception:   # noqa: BLE001 — share is best-effort
                logger.warning("router: tier prefix share failed",
                               exc_info=True)
            return rid
        if n_pool_exhausted == len(order):
            # every replica said never-fits — structural, not load
            raise last_pool_exhausted
        self.router_stats["rejected_tier"] += 1
        r.counter("serving.router.rejected", reason="tier_saturated").inc()
        raise Rejected(
            "tier_saturated",
            f"all {len(order)} placeable replicas shed the request")

    # ------------------------------------------------------ health machine
    def _heartbeat(self, i: int, rep: _Replica):
        """One heartbeat probe: the ``router.heartbeat`` fault site
        (a raising fault IS a miss), then liveness (a closed engine is
        definitively dead — no grace period). Cross-process replicas
        add a WALL-CLOCK ping: a worker that does not answer inside
        ``heartbeat_timeout_s`` — hung, not just dead — is a miss, and
        an EOF (the process is gone) is declared dead immediately."""
        from paddle_tpu.observability import registry
        from paddle_tpu.resilience import faults as _faults

        ok = True
        try:
            _faults.maybe_fire("router.heartbeat")
        except Exception:   # noqa: BLE001 — injected miss, any kind
            ok = False
        if rep.engine is None or rep.engine.closed:
            self._declare_dead(i, rep, "engine_closed")
            return
        if ok and hasattr(rep.engine, "ping"):
            ok = rep.engine.ping(timeout_s=self.heartbeat_timeout_s)
            if rep.engine.closed:
                self._declare_dead(i, rep, "worker_gone")
                return
        if ok:
            rep.misses = 0
            if rep.state == "suspect":
                rep.state = "healthy"
            return
        rep.misses += 1
        self.router_stats["heartbeat_misses"] += 1
        registry().counter("serving.router.heartbeat_misses",
                           replica=str(i)).inc()
        if rep.misses >= self.dead_after:
            self._declare_dead(i, rep, "missed_heartbeats")
        elif rep.misses >= self.suspect_after \
                and rep.state == "healthy":
            rep.state = "suspect"

    def _declare_dead(self, i: int, rep: _Replica, why: str):
        from paddle_tpu.observability import registry
        if rep.state == "dead":
            return
        rep.state = "dead"
        self.router_stats["replica_deaths"] += 1
        registry().counter("serving.router.replica_deaths").inc()
        if self._tier_prefix is not None:
            # its cached blocks died with it — drop every stale hint
            self._tier_prefix.forget_replica(i)
        self.flight.mark("replica_dead", replica=i, why=why)
        logger.warning("router: replica %d declared dead (%s)", i, why)

    # ------------------------------------------------------------ failover
    def _absorb_stats(self, eng: Optional[ServingEngine]):
        """Accumulate a retiring engine's cumulative stats so the
        tier-level ``stats`` survives replica replacement."""
        if eng is None or not isinstance(getattr(eng, "stats", None),
                                         dict):
            return
        for k, v in eng.stats.items():
            if isinstance(v, (int, float)):
                self._stats_base[k] = self._stats_base.get(k, 0) + v
        pc = getattr(eng, "prefix_cache", None)
        if pc is not None:
            try:
                self._prefix_base[0] += int(pc.hit_blocks)
                self._prefix_base[1] += int(pc.lookup_blocks)
            except Exception:   # noqa: BLE001 — telemetry best-effort
                pass

    def _restore_engine(self, i: int, rep: _Replica):
        """Try to bring replica ``i`` back from its snapshot root.
        Returns ``(engine_or_None, covered_rids, mode)`` where mode is
        "restore" (the engine resumed its snapshotted slots/queue
        token-exactly) or "redistribute" (nothing restored — the caller
        re-places tracked work).  In processes mode the RESTORE RUNS IN
        THE CHILD: a fresh worker is spawned with ``restore_root`` and
        reports what it covered through the handshake, so the parent
        never deserializes worker state."""
        if self.processes:
            try:
                eng = self._new_engine(i, restore_root=rep.root)
            except Exception:   # noqa: BLE001 — spawn/handshake failed
                logger.warning("router: replica %d worker respawn "
                               "failed", i, exc_info=True)
                return None, set(), "redistribute"
            if getattr(eng, "restored", False):
                return eng, set(eng.covered), "restore"
            return eng, set(), "redistribute"
        try:
            snap = ServingEngine.load_snapshot(rep.root)
            eng = ServingEngine.restore(self.model, snap,
                                        state=self._state,
                                        **self._restore_overrides(i))
            covered = {rs["request_id"]
                       for rs in snap["slots"] + snap["queue"]}
            return eng, covered, "restore"
        except FileNotFoundError:
            return None, set(), "redistribute"   # never snapshotted
        except (RestoreError, ValueError, KeyError):
            logger.warning("router: replica %d snapshot unusable; "
                           "redistributing", i, exc_info=True)
            return None, set(), "redistribute"

    def _failover(self, i: int):
        """Rebuild dead replica ``i`` zero-loss: restore from its last
        committed-and-verified snapshot when possible (the restored
        engine resumes its own slots/queue token-exactly), else rebuild
        it empty; every tracked unfinished request the restored
        snapshot does NOT cover is re-placed with its generated-so-far
        tokens through the resume path."""
        from paddle_tpu.observability import registry

        rep = self._replicas[i]
        tracked = [t for t in self._requests.values()
                   if t.replica == i and not t.finished]
        old = rep.engine
        self._absorb_stats(old)
        if old is not None:
            try:
                old.close()
            except Exception:   # noqa: BLE001 — best-effort release
                pass
        eng = None
        covered = set()
        mode = "redistribute"
        if rep.root is not None:
            eng, covered, mode = self._restore_engine(i, rep)
        if eng is not None and mode != "restore" and not self.rebuild_dead:
            # a fresh (nothing-restored) worker came up but the tier is
            # configured to shrink on death rather than rebuild
            try:
                eng.close()
            except Exception:   # noqa: BLE001 — best-effort release
                pass
            eng = None
        if eng is None and self.rebuild_dead:
            try:
                eng = self._new_engine(i)
            except Exception:   # noqa: BLE001 — spawn/build failed
                logger.warning("router: replica %d rebuild failed; "
                               "removing from tier", i, exc_info=True)
                eng = None
        if eng is not None:
            rep.engine = eng
            rep.state = "healthy"
            rep.misses = 0
        else:
            rep.engine = None
            rep.state = "removed"
        # a request the snapshot covers is already queued for resume on
        # the restored engine; anything newer (accepted after the
        # snapshot) or uncovered re-places across the tier
        for t in tracked:
            if mode == "restore" and t.rid in covered:
                continue
            self._queue_replace(t)
        self.router_stats["failovers"] += 1
        registry().counter("serving.router.failovers", mode=mode).inc()
        self.flight.mark("failover", replica=i, mode=mode,
                         covered=len(covered), replaced=len(
                             [t for t in tracked
                              if not (mode == "restore"
                                      and t.rid in covered)]))
        if self.journal is not None:
            self.journal.append("failover", replica=i, mode=mode)
        self.flight.auto_dump("failover")

    def _queue_replace(self, t: _Tracked):
        t.replica = None
        if t not in self._pending_replace:
            self._pending_replace.append(t)

    def _drain_pending_replacements(self):
        """Re-place queued orphans onto the tier (ALL of them or raise
        only structurally — a momentary no-placeable-replica window
        just leaves them pending for the next tick)."""
        from paddle_tpu.observability import registry
        if not self._pending_replace:
            return
        still = []
        for t in self._pending_replace:
            req = t.as_request()
            phase = "decode" if t.tokens else "prefill"
            order, _ = self._placement_order(req, phase)
            if not order:
                still.append(t)
                continue
            idx = order[0]
            # ship the prompt's finished prefill blocks ahead of the
            # resume so its re-prefill is a block copy, not a recompute
            # — for a role migration this IS the block-transfer path
            # PR 19 left open (journaled as "migrate_blocks")
            try:
                self._share_prefix(
                    idx, t.prompt, rid=t.rid,
                    event=("migrate_blocks"
                           if t.rid in self._migrating
                           else "prefix_share"))
            except Exception:   # noqa: BLE001 — share is best-effort
                logger.warning("router: tier prefix share failed",
                               exc_info=True)
            self._migrating.discard(t.rid)
            # admit_resumable bypasses the overload controls: this
            # request was ACCEPTED — shedding it now would be data loss
            try:
                self._replicas[idx].engine.admit_resumable(
                    req, tokens=t.tokens)
            except Rejected:
                # the worker became unreachable between the placement
                # decision and the RPC — stay pending for the next tick
                # (its failover runs first)
                still.append(t)
                continue
            t.replica = idx
            self.router_stats["replaced"] += 1
            registry().counter("serving.router.replaced").inc()
            if self.journal is not None:
                self.journal.append("place", rid=t.rid, replica=idx,
                                    trace_id=t.trace_id,
                                    tokens=len(t.tokens))
        self._pending_replace = still

    # ------------------------------------------------------------ stepping
    def step(self) -> Dict:
        """One tier tick: heartbeat every replica, fail over the dead,
        re-place orphans, step every live replica once, mirror
        generated-so-far progress, collect finished results, and run
        the journal/snapshot cadences. Returns
        ``{"active", "queued", "finished"}`` aggregated over the tier.
        An exception out of a replica's ``step()`` is a replica-level
        event (snapshot + declare dead + failover), never a router
        crash."""
        if self._closed:
            raise RuntimeError("Router is closed")
        self._tick += 1
        finished: List[int] = []
        for i, rep in enumerate(self._replicas):
            if rep.state in ("healthy", "suspect", "draining"):
                self._heartbeat(i, rep)
        for i, rep in enumerate(self._replicas):
            if rep.state == "dead":
                self._failover(i)
        self._drain_pending_replacements()
        for i, rep in enumerate(self._replicas):
            if rep.state not in ("healthy", "suspect", "draining") \
                    or rep.engine is None or rep.engine.closed:
                continue
            if rep.engine.idle:
                continue
            try:
                out = rep.engine.step()
            except Exception as e:      # noqa: BLE001 — replica crash
                self._on_step_crash(i, rep, e)
                continue
            self._collect(i, rep, out["finished"], finished)
        self._track_progress()
        self._migrate_roles()
        self._heal_orphans()
        if self.journal is not None \
                and self._tick % self.journal_progress_every == 0:
            self._journal_progress()
        if self.snapshot_every \
                and self._tick % self.snapshot_every == 0:
            self._snapshot_next()
        active = sum(r.engine.active_slots for r in self._replicas
                     if r.engine is not None and not r.engine.closed)
        queued = sum(r.engine.queued for r in self._replicas
                     if r.engine is not None and not r.engine.closed)
        queued += len(self._pending_replace)
        self.flight.record({
            "step": self._tick, "ts": round(time.time(), 6),
            "ts_mono": round(time.perf_counter(), 6),
            "active": active, "queued": queued,
            "finished": list(finished),
            "pending_replace": len(self._pending_replace),
            "replicas": [
                {"i": i, "state": r.state, "misses": r.misses,
                 "active": (r.engine.active_slots
                            if r.engine is not None
                            and not r.engine.closed else 0),
                 "queued": (r.engine.queued
                            if r.engine is not None
                            and not r.engine.closed else 0)}
                for i, r in enumerate(self._replicas)]})
        self._update_gauges()
        if self.watchdog is not None \
                and self._tick % self.watchdog.check_every == 0:
            self.watchdog.check(source=self)
        return dict(active=active, queued=queued, finished=finished)

    def _on_step_crash(self, i: int, rep: _Replica, exc: BaseException):
        """A replica's tick died. The PR 8 contract keeps the engine's
        host scheduler state consistent across an aborted tick, so
        snapshot it NOW — failover then restores with zero recompute —
        and if even the snapshot fails, the in-memory journal mirror
        still re-places everything (redistribute path)."""
        from paddle_tpu.resilience.retry import call_with_retry

        logger.warning("router: replica %d step crashed: %s: %s",
                       i, type(exc).__name__, exc)
        self.flight.mark("replica_step_crash", replica=i,
                         err=f"{type(exc).__name__}: {exc}")
        if rep.root is not None and rep.engine is not None \
                and not rep.engine.closed:
            try:
                call_with_retry(
                    lambda: rep.engine.save_snapshot(rep.root),
                    policy=self.retry_policy, retry_on=(OSError,),
                    describe="router.snapshot")
            except Exception:   # noqa: BLE001 — fall back to re-place
                logger.warning("router: crash snapshot of replica %d "
                               "failed; will redistribute", i,
                               exc_info=True)
        self._declare_dead(i, rep, "step_exception")
        self._failover(i)

    def _rescue_shed(self, t: _Tracked, res: RequestResult,
                     exclude: int) -> bool:
        """An engine displaced a queued ACCEPTED request to make room
        for higher-priority work (``finish="shed"``). At tier level
        that is only final if the whole tier is out of room — try the
        OTHER replicas through the normal overload-controlled submit
        first: a terminal shed while a sibling replica sits idle is a
        router failure, but shedding at true tier saturation is the
        correct typed outcome (every displacement victim is strictly
        lower-priority than its displacer, so rescue chains terminate).
        Returns True when the request found a new home."""
        from paddle_tpu.observability import registry

        req = t.as_request()
        req._resume_tokens = [int(x) for x in res.tokens] or None
        phase = "decode" if req._resume_tokens else "prefill"
        order, _ = self._placement_order(req, phase)
        for idx in order:
            if idx == exclude:
                continue
            try:
                self._replicas[idx].engine.submit(req)
            except (Rejected, PoolExhausted):
                continue
            t.replica = idx
            t.tokens = [int(x) for x in res.tokens]
            self.router_stats["replaced"] += 1
            registry().counter("serving.router.replaced").inc()
            if self.journal is not None:
                self.journal.append("place", rid=t.rid, replica=idx,
                                    trace_id=t.trace_id,
                                    tokens=len(t.tokens))
            return True
        return False

    def _collect(self, i: int, rep: _Replica, finished_ids, finished):
        for rid in finished_ids:
            res = rep.engine.results.pop(rid, None)
            if res is None:
                continue
            t = self._requests.get(rid)
            if t is not None and t.finished:
                continue        # duplicate re-run after a failover
            if res.finish == "shed" and t is not None \
                    and self._rescue_shed(t, res, exclude=i):
                continue        # re-placed on a replica with room
            if t is not None:
                t.finished = True
                t.tokens = [int(x) for x in res.tokens]
            self._open.discard(rid)
            if rid in self.results:
                continue
            self.results[rid] = res
            finished.append(rid)
            if self.journal is not None and t is not None:
                self.journal.append(
                    "finish", rid=rid, finish=res.finish,
                    trace_id=t.trace_id,
                    tokens=[int(x) for x in res.tokens],
                    gen_len=res.gen_len, ttft_s=res.ttft_s,
                    tpot_s=res.tpot_s)

    def _track_progress(self):
        """Mirror each live replica's generated-so-far tokens into the
        tracked map — what failover re-places with. Any PREFIX of the
        true stream is token-exact under resume, so a stale mirror only
        costs recompute, never correctness."""
        for rep in self._replicas:
            if rep.engine is None or rep.engine.closed:
                continue
            for rid, toks in rep.engine.inflight_tokens().items():
                t = self._requests.get(rid)
                if t is not None and not t.finished:
                    t.tokens = toks

    def _migrate_roles(self):
        """Disaggregated role scheduling (PAPERS.md: prefill/decode
        separation): a request on a PREFILL-role replica migrates to a
        decode-capable replica at its first token, through the same
        token-exact release → re-admit path failover uses. Roles are a
        routing preference, never a correctness gate: with no
        decode-capable replica placeable the request degrades in place
        (the prefill replica keeps decoding it)."""
        from paddle_tpu.observability import registry

        if all(r.role == ReplicaRole.MIXED for r in self._replicas):
            return
        moved = 0
        for t in self._requests.values():
            if t.finished or t.replica is None or not t.tokens:
                continue
            rep = self._replicas[t.replica]
            if rep.role != ReplicaRole.PREFILL or rep.engine is None \
                    or rep.engine.closed:
                continue
            if not any(self._replicas[i].role in
                       (ReplicaRole.DECODE, ReplicaRole.MIXED)
                       for i in self._placeable()):
                continue    # nowhere decode-capable — degrade in place
            toks = rep.engine.release_request(t.rid)
            if toks is None:
                continue    # already finished/collected — not held
            t.tokens = [int(x) for x in toks]
            self._queue_replace(t)
            # the prefill replica's cache still holds the prompt's
            # finished blocks (its own refs survive the release) — mark
            # the re-placement a migration so the share journals
            # "migrate_blocks" when the decode side adopts them
            self._migrating.add(t.rid)
            moved += 1
        if moved:
            self.router_stats["role_migrations"] = \
                self.router_stats.get("role_migrations", 0) + moved
            registry().counter("serving.router.role_migrations").inc(moved)
            self.flight.mark("role_migration", moved=moved)
            # re-place NOW (journals "place" with the trace_id, so the
            # accept→place→finish chain stays connected) rather than
            # waiting a tick with the request in limbo
            self._drain_pending_replacements()

    def _heal_orphans(self):
        """A tracked unfinished request held by NO live replica (e.g. a
        failover raced a retirement, or a kill dropped an uncollected
        result) re-enters placement — the belt under the suspenders
        that makes ``drain()`` always terminate or raise loudly."""
        held = set()
        for rep in self._replicas:
            if rep.engine is None or rep.engine.closed:
                continue
            held.update(rep.engine.inflight_tokens().keys())
            held.update(rep.engine.results.keys())
        pending = {t.rid for t in self._pending_replace}
        for t in self._requests.values():
            if not t.finished and t.rid not in held \
                    and t.rid not in pending:
                self._queue_replace(t)

    def _journal_progress(self):
        changed = {}
        for t in self._requests.values():
            if not t.finished and len(t.tokens) > t.journaled_tokens:
                changed[str(t.rid)] = t.tokens
                t.journaled_tokens = len(t.tokens)
        if changed:
            self.journal.append("progress", tokens=changed)

    def _snapshot_next(self):
        """Round-robin one live replica through the integrity-manifest
        snapshot path (one per cadence tick bounds the stall)."""
        from paddle_tpu.observability import registry
        from paddle_tpu.resilience.retry import call_with_retry

        live = self.live_replicas
        if not live or self.root is None:
            return
        i = live[self._snap_cursor % len(live)]
        self._snap_cursor += 1
        rep = self._replicas[i]
        try:
            call_with_retry(
                lambda: rep.engine.save_snapshot(rep.root),
                policy=self.retry_policy, retry_on=(OSError,),
                describe="router.snapshot")
            self.router_stats["snapshots"] += 1
            registry().counter("serving.router.snapshots").inc()
        except Exception:   # noqa: BLE001 — cadence must not kill a tick
            logger.warning("router: periodic snapshot of replica %d "
                           "failed", i, exc_info=True)
            self.flight.mark("snapshot_failed", replica=i)

    # --------------------------------------------------------- elasticity
    def drain_replica(self, i: int,
                      timeout_s: Optional[float] = None) -> List[int]:
        """Elastic drain: stop placement to replica ``i``, snapshot it
        (postmortem trail), migrate its in-flight and queued work onto
        the survivors via the token-exact resume path, and remove it.
        Returns the migrated request ids. Draining the last live
        replica raises — the work would have nowhere to go. With
        ``timeout_s`` a cross-process replica that does not answer a
        liveness ping inside the budget raises :class:`DrainTimeout`
        naming the stuck replica and its queue depth — a hung worker
        must surface as a typed error, not an indefinite drain."""
        from paddle_tpu.observability import registry
        from paddle_tpu.resilience.retry import call_with_retry

        rep = self._replicas[i]
        if rep.state not in ("healthy", "suspect", "draining") \
                or rep.engine is None or rep.engine.closed:
            raise ValueError(f"replica {i} is {rep.state}; only a live "
                             f"replica can be drained")
        if len(self.live_replicas) <= 1:
            raise ValueError("cannot drain the last live replica — its "
                             "work would have nowhere to migrate "
                             "(add_replica first)")
        if timeout_s is not None and hasattr(rep.engine, "ping") \
                and not rep.engine.ping(timeout_s=timeout_s):
            depth = 0
            try:
                depth = int(rep.engine.queued)
            except Exception:   # noqa: BLE001 — best-effort depth
                pass
            raise DrainTimeout(
                f"drain_replica({i}): worker did not answer a liveness "
                f"ping within {timeout_s}s (queue depth {depth})",
                replica=i, queue_depth=depth)
        rep.state = "draining"
        if rep.root is not None:
            try:
                call_with_retry(
                    lambda: rep.engine.save_snapshot(rep.root),
                    policy=self.retry_policy, retry_on=(OSError,),
                    describe="router.snapshot")
            except Exception:   # noqa: BLE001 — drain proceeds anyway
                logger.warning("router: drain snapshot of replica %d "
                               "failed", i, exc_info=True)
        # freshest possible resume state, straight from the live engine
        inflight = rep.engine.inflight_tokens()
        migrated = []
        for rid, toks in inflight.items():
            t = self._requests.get(rid)
            if t is None or t.finished:
                continue
            t.tokens = toks
            self._queue_replace(t)
            migrated.append(rid)
        self._absorb_stats(rep.engine)
        try:
            rep.engine.close()
        except Exception:   # noqa: BLE001 — best-effort release
            pass
        rep.engine = None
        rep.state = "removed"
        if self._tier_prefix is not None:
            self._tier_prefix.forget_replica(i)
        self._drain_pending_replacements()
        self.router_stats["drains"] += 1
        registry().counter("serving.router.drains").inc()
        self.flight.mark("drain", replica=i, migrated=len(migrated))
        if self.journal is not None:
            self.journal.append("drain", replica=i, migrated=migrated)
        self.flight.auto_dump("drain")
        self._update_gauges()
        return migrated

    def add_replica(self, warm: bool = True,
                    role: str = ReplicaRole.MIXED) -> int:
        """Grow the tier by one replica; returns its index. With
        ``warm=True`` (default) a throwaway one-block request is run to
        completion first, so the replica's smallest prefill bucket and
        its step program are compiled BEFORE it takes traffic — "joins
        warm". For a tensor-parallel tier the warmup runs UNDER THE
        REPLICA'S OWN MESH context (asserted below): the engine's
        programs carry their mesh explicitly through ``shard_map``, but
        entering the context pins any ambient-mesh-sensitive lowering
        (and any future jit cache keyed on the mesh context) to the
        same programs the replica will re-dispatch under traffic — a
        warmup compiled under a DIFFERENT ambient mesh would be paid
        for twice. Affinity hashing uses the slot count, so existing
        prefixes keep their homes and only the new slot's share moves."""
        import contextlib

        from paddle_tpu.observability import registry

        if role not in REPLICA_ROLES:
            raise ValueError(f"unknown replica role {role!r} "
                             f"(choose from {REPLICA_ROLES})")
        idx = len(self._replicas)
        rep = _Replica(self._new_engine(idx), self._replica_root(idx),
                       role=role)
        if warm:
            mesh = rep.engine.mesh
            with (mesh if mesh is not None else contextlib.nullcontext()):
                if mesh is not None:
                    from jax.interpreters import pxla
                    active = pxla.thread_resources.env.physical_mesh
                    assert active is mesh, (
                        "add_replica warmup must run under the "
                        "replica's own mesh context")
                bt = rep.engine.block_tokens
                # tpu-lint: allow(host-sync): host-built warmup prompt
                prompt = np.full(min(bt, rep.engine.max_seq_len - 2), 3,
                                 np.int32)
                rid = rep.engine.submit(Request(prompt, max_new_tokens=1,
                                                seed=0))
                rep.engine.drain(max_steps=64)
                rep.engine.results.pop(rid, None)
                rep.engine.reset_stats()
        self._replicas.append(rep)
        registry().counter("serving.router.replicas_added").inc()
        self.flight.mark("add_replica", replica=idx, warm=warm)
        if self.journal is not None:
            self.journal.append("add_replica", replica=idx)
        self._update_gauges()
        return idx

    def kill_replica(self, i: int, mode: str = "close"):
        """Chaos hook: simulate abrupt replica death. ``mode="close"``
        (default, works for any tier) drops the engine's device state,
        queue, slots AND uncollected results on the floor — no
        snapshot, no goodbye. ``mode="sigkill"`` (cross-process tiers
        only) sends a REAL ``SIGKILL`` to the worker process, armed to
        land MID-STEP when the worker is busy — the kernel tears the
        process down between a request's tokens, the hardest point in
        a tick. Either way the router only finds out at the next
        tick's heartbeat, exactly like a real crash; the zero-loss
        contract must hold anyway (tests/test_serving_router.py,
        examples/chaos_bench.py --kill_mode)."""
        from paddle_tpu.observability import registry

        if mode not in ("close", "sigkill"):
            raise ValueError(f"unknown kill mode {mode!r} "
                             f"(choose 'close' or 'sigkill')")
        rep = self._replicas[i]
        if rep.engine is None or rep.engine.closed:
            raise ValueError(f"replica {i} is already gone")
        if mode == "sigkill" and not hasattr(rep.engine, "kill"):
            raise ValueError("kill_replica(mode='sigkill') needs a "
                             "cross-process tier (Router(processes="
                             "True)) — an in-process engine has no pid")
        self.router_stats["replica_kills"] += 1
        registry().counter("serving.router.replica_kills").inc()
        self.flight.mark("replica_killed", replica=i, mode=mode)
        if mode == "sigkill":
            rep.engine.kill(mid_step=True)
        else:
            rep.engine.close()  # drops everything, stats included

    # ------------------------------------------------- bench duck-typing
    _UNSET = object()

    def set_overload_controls(self, *, max_queue=_UNSET,
                              shed_infeasible=_UNSET):
        """Flip the PR 8 overload knobs on every live replica AND on
        the template config future replicas (failover rebuilds,
        :meth:`add_replica`) are built from — the benches calibrate
        unshedded (a saturated closed-loop warmup would shed itself)
        and arm shedding for the measured pass."""
        for rep in self._replicas:
            if rep.engine is None or rep.engine.closed:
                continue
            if max_queue is not self._UNSET:
                rep.engine.max_queue = max_queue
            if shed_infeasible is not self._UNSET:
                rep.engine.shed_infeasible = bool(shed_infeasible)
        if max_queue is not self._UNSET:
            self._engine_kwargs["max_queue"] = max_queue
        if shed_infeasible is not self._UNSET:
            self._engine_kwargs["shed_infeasible"] = bool(shed_infeasible)

    @property
    def stats(self) -> Dict:
        """Tier-cumulative stats: the sum of every engine's counters
        (incl. engines retired by failover/drain — their last readable
        stats are absorbed) plus the ``router_*`` tier counters."""
        out = dict(self._stats_base)
        for rep in self._replicas:
            if rep.engine is None \
                    or not isinstance(rep.engine.stats, dict):
                continue
            for k, v in rep.engine.stats.items():
                if isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
        for k, v in self.router_stats.items():
            out[f"router_{k}"] = v
        return out

    def metrics_snapshot(self) -> "MetricsRegistry":
        """The tier metrics plane: one merged :class:`MetricsRegistry`
        folding every replica's ``replica="<i>"``-labeled series into
        tier totals — counters summed, histograms bucket-summed,
        quantile sketches :meth:`~QuantileSketch.merge`-d, gauges kept
        per-replica-labeled (a summed occupancy gauge is meaningless;
        a per-replica one is a dashboard row). The merged registry is
        a detached point-in-time copy with the full export surface
        (``export_jsonl`` / ``prometheus_text``); mutating it does not
        touch the live series (docs/OBSERVABILITY.md §Tier metrics).
        The tier-merged prefix gauges (``serving.router.
        prefix_hit_rate`` / ``tier_prefix_hit_rate``) are refreshed
        first so the snapshot carries them even between ticks."""
        from paddle_tpu.observability import registry
        self._update_gauges()
        return registry().merged_across("replica")

    def reset_stats(self):
        self._stats_base = {}
        self._prefix_base = [0, 0]
        if self._tier_prefix is not None:
            self._tier_prefix.hit_blocks = 0
            self._tier_prefix.lookup_blocks = 0
        for rep in self._replicas:
            if rep.engine is not None and not rep.engine.closed:
                rep.engine.reset_stats()
        for k in self.router_stats:
            self.router_stats[k] = 0

    @property
    def pool_blocks_total(self) -> int:
        """Usable KV blocks across live replicas (scratch excluded)."""
        return sum(r.engine.pool.num_blocks - 1 for r in self._replicas
                   if r.engine is not None and not r.engine.closed)

    @property
    def prefix_hit_rate(self) -> float:
        """Block-weighted prefix hit rate over the WHOLE tier: live
        replicas plus the absorbed counters of engines retired by
        failover/drain — a router-mode bench that killed a replica
        mid-run must not lose that replica's reuse accounting (the
        per-replica-only rate this replaces under-reported exactly
        when the tier was doing its job)."""
        hits, lookups = self._prefix_base
        for r in self._replicas:
            if r.engine is None or r.engine.closed \
                    or r.engine.prefix_cache is None:
                continue
            hits += r.engine.prefix_cache.hit_blocks
            lookups += r.engine.prefix_cache.lookup_blocks
        return hits / lookups if lookups else 0.0

    @property
    def tier_prefix_hit_rate(self) -> float:
        """Fraction of placement-probed prefix blocks served by a
        CROSS-REPLICA block copy through the tier store — the reuse
        the per-replica caches cannot see (0.0 with the store off)."""
        return (self._tier_prefix.hit_rate
                if self._tier_prefix is not None else 0.0)

    @property
    def tier_prefix_store(self) -> Optional[TierPrefixStore]:
        return self._tier_prefix

    def clear_prefix_caches(self):
        for r in self._replicas:
            if r.engine is not None and not r.engine.closed \
                    and r.engine.prefix_cache is not None:
                r.engine.prefix_cache.clear()
        if self._tier_prefix is not None:
            self._tier_prefix.clear()

    @property
    def active_slots(self) -> int:
        return sum(r.engine.active_slots for r in self._replicas
                   if r.engine is not None and not r.engine.closed)

    @property
    def queued(self) -> int:
        return (sum(r.engine.queued for r in self._replicas
                    if r.engine is not None and not r.engine.closed)
                + len(self._pending_replace))

    @property
    def idle(self) -> bool:
        """The tier is idle only when NOTHING can still make progress:
        no orphan awaiting re-placement, no accepted request
        unfinished, and no replica that is dead — or killed but not
        yet discovered (a closed engine in a live state means the next
        tick's heartbeat will declare it dead and fail over; treating
        that as idle would let a drive loop exit between the kill and
        the failover, silently losing its requests)."""
        if self._pending_replace or self._open:
            return False
        for r in self._replicas:
            if r.state == "dead":
                return False
            if r.state in ("healthy", "suspect", "draining"):
                if r.engine is None or r.engine.closed:
                    return False
                if not r.engine.idle:
                    return False
        return True

    def pop_result(self, request_id: int) -> RequestResult:
        return self.results.pop(request_id)

    def _stuck_replica(self):
        """(index, queue depth) of the live replica holding the most
        work — the best available name for WHO is stuck when a drain
        times out — or ``(None, pending_replace depth)`` when nothing
        live holds anything (the work is orphaned, not held)."""
        best, best_depth = None, -1
        for i, r in enumerate(self._replicas):
            if r.engine is None or r.engine.closed:
                continue
            try:
                depth = int(r.engine.active_slots) + int(r.engine.queued)
            except Exception:   # noqa: BLE001 — unreachable counts as 0
                continue
            if depth > best_depth:
                best, best_depth = i, depth
        if best is None or best_depth <= 0:
            return None, len(self._pending_replace)
        return best, best_depth

    def drain(self, max_steps: Optional[int] = None,
              timeout_s: Optional[float] = None
              ) -> Dict[int, RequestResult]:
        """Step until every accepted request has finished (or
        ``max_steps``). A tier that makes no progress for several
        consecutive all-idle ticks raises ``RuntimeError`` instead of
        spinning (the router self-heals orphans each tick, so a real
        stall means something structural). With ``timeout_s`` a drain
        that outlives the wall-clock budget raises
        :class:`DrainTimeout` naming the stuck replica and its queue
        depth — the caller gets WHO, not just "too slow"."""
        steps = idle_spins = 0
        t0 = time.perf_counter()
        while not self.idle:
            if timeout_s is not None \
                    and time.perf_counter() - t0 > timeout_s:
                idx, depth = self._stuck_replica()
                who = (f"replica {idx}" if idx is not None
                       else "no live replica (orphaned work)")
                raise DrainTimeout(
                    f"drain exceeded {timeout_s}s: {who} still holds "
                    f"{depth} request(s)", replica=idx, queue_depth=depth)
            out = self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            # orphans with NO placeable replica count as stalled too —
            # they sit in pending_replace (reported under "queued") and
            # can never progress, so waiting on them would spin forever
            stuck_orphans = bool(self._pending_replace) \
                and not self._placeable()
            if out["active"] == 0 and not out["finished"] \
                    and (out["queued"] == 0 or stuck_orphans):
                idle_spins += 1
                if idle_spins > 8:
                    raise RuntimeError(
                        "router drain stalled: no replica can make "
                        "progress but tracked requests are unfinished"
                        + (f" ({len(self._pending_replace)} orphans "
                           f"with no placeable replica)"
                           if stuck_orphans else ""))
            else:
                idle_spins = 0
        return self.results

    def generate(self, prompts: Sequence, **req_kwargs) -> List:
        """Batch convenience mirroring ``ServingEngine.generate``."""
        # tpu-lint: allow(host-sync): API boundary — prompts are host ids
        ids = [self.submit(Request(np.asarray(p).reshape(-1),
                                   **req_kwargs)) for p in prompts]
        self.drain()
        return [self.results[i].ids for i in ids]

    def close(self):
        if self._closed:
            return
        self._closed = True
        for rep in self._replicas:
            if rep.engine is not None:
                try:
                    rep.engine.close()
                except Exception:   # noqa: BLE001 — best-effort
                    pass
                rep.engine = None
            rep.state = "removed"
        if self._tier_prefix is not None:
            self._tier_prefix.clear()
        if self.journal is not None:
            self.journal.append("close")

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------- router recovery
    @classmethod
    def recover(cls, model, root: str, *, state=None,
                **router_kwargs) -> "Router":
        """Rebuild a whole tier after a ROUTER-process crash: replay
        the journal (corrupt lines skipped and counted), restore every
        replica whose snapshot verifies, rebuild the rest empty, and
        re-place every journaled accepted-but-unfinished request — with
        its last journaled token progress — through the resume path.
        Finished results are reconstructed from their journal records.
        ``router_kwargs`` mirror the constructor (engine knobs
        included) and must match the crashed router's config, exactly
        like ``ServingEngine.restore`` overrides."""
        events, corrupt = RouterJournal.replay(
            os.path.join(root, "journal.jsonl"))
        if corrupt:
            logger.warning("router recovery: skipped %d corrupt journal "
                           "lines", corrupt)
        header = next((e for e in events if e.get("kind") == "header"),
                      None)
        n_replicas = router_kwargs.pop(
            "replicas", header.get("replicas", 2) if header else 2)
        if header is not None and "seed" not in router_kwargs:
            router_kwargs["seed"] = header.get("seed", 0)
        rt = cls(model, replicas=n_replicas, state=state, root=root,
                 **router_kwargs)
        # journal fold: accept -> place -> progress -> finish, in order
        accepted: Dict[int, Dict] = {}
        for e in events:
            k = e.get("kind")
            if k == "accept":
                accepted[e["rid"]] = dict(e, tokens=[])
            elif k == "place" and e.get("rid") in accepted:
                accepted[e["rid"]]["replica"] = e.get("replica")
            elif k == "progress":
                for rid_s, toks in e.get("tokens", {}).items():
                    rid = int(rid_s)
                    if rid in accepted:
                        accepted[rid]["tokens"] = toks
            elif k == "finish" and e.get("rid") in accepted:
                accepted[e["rid"]]["finish"] = e
        # re-anchor the seed source past every router-assigned seed in
        # the journal: a recovered router that reset _seeds_issued to 0
        # would mint the SAME seed for its next fresh submit as the
        # first pre-crash request drew — two requests sharing one RNG
        # stream (the snapshot-coverage audit's find; engine restore
        # already carries seeds_issued in its snapshot for the same
        # reason)
        rt._seeds_issued = max(
            [rt._seeds_issued]
            + [e["seed"] - rt.seed + 1 for e in accepted.values()
               if isinstance(e.get("seed"), int)
               and e["seed"] >= rt.seed])
        # replicas were built fresh by the constructor; swap in restored
        # engines where a committed snapshot survives
        covered = set()
        for i, rep in enumerate(rt._replicas):
            if rep.root is None:
                continue
            if rt.processes:
                from paddle_tpu.resilience import integrity as _integ
                if not _integ.manifest_steps(rep.root):
                    continue    # never committed a snapshot — keep fresh
                try:
                    rep.engine.close()
                except Exception:   # noqa: BLE001 — being replaced
                    pass
                eng, cov, mode = rt._restore_engine(i, rep)
                if eng is None:
                    rep.engine = None
                    rep.state = "removed"
                    continue
                rep.engine = eng
                if mode == "restore":
                    covered |= cov
                continue
            try:
                snap = ServingEngine.load_snapshot(rep.root)
            except FileNotFoundError:
                continue
            # free the constructor-built engine BEFORE the restore
            # allocates its pool — restoring a fully-snapshotted tier
            # must not transiently double per-replica device memory
            rep.engine.close()
            try:
                eng = ServingEngine.restore(
                    model, snap, state=rt._state,
                    **rt._restore_overrides(i))
            except (RestoreError, ValueError, KeyError):
                logger.warning("router recovery: replica %d snapshot "
                               "unusable", i, exc_info=True)
                rep.engine = rt._new_engine(i)
                continue
            rep.engine = eng
            covered |= {rs["request_id"]
                        for rs in snap["slots"] + snap["queue"]}
        for rid, rec in accepted.items():
            fin = rec.get("finish")
            # tpu-lint: allow(host-sync): journal JSON is host data
            prompt = np.asarray(rec["prompt"], np.int32)
            if fin is not None:
                rt.results[rid] = RequestResult(
                    rid, prompt, fin.get("tokens", []),
                    fin.get("gen_len", len(fin.get("tokens", []))),
                    fin.get("finish", "length"), fin.get("ttft_s"),
                    fin.get("tpot_s"), 0,
                    trace_id=rec.get("trace_id"))
                t = _Tracked(rid, prompt, rec["max_new_tokens"],
                             rec["seed"], rec.get("priority", "normal"),
                             None, None, trace_id=rec.get("trace_id"))
                t.finished = True
                t.tokens = list(fin.get("tokens", []))
                rt._requests[rid] = t
                continue
            t = _Tracked(rid, prompt, rec["max_new_tokens"], rec["seed"],
                         rec.get("priority", "normal"),
                         rec.get("deadline_s"), rec.get("replica"),
                         trace_id=rec.get("trace_id"))
            t.tokens = list(rec.get("tokens", []))
            rt._requests[rid] = t
            rt._open.add(rid)
            if rid not in covered:
                rt._queue_replace(t)
        rt._drain_pending_replacements()
        rt.flight.mark("recover", requests=len(accepted),
                       covered=len(covered),
                       corrupt_journal_lines=corrupt)
        if rt.journal is not None:
            rt.journal.append("recover", requests=len(accepted),
                              corrupt=corrupt)
        return rt
