"""Durable request journal + the journal-event registry.

The serving tier's zero-loss story rests on two write paths: replica
snapshots (``ServingEngine.save_snapshot``, integrity-manifest
committed) and THIS append-only CRC-framed journal — the router logs
every request-state transition it owns (accept / place / progress /
finish / failover / drain / ...) so a dead replica or a crashed router
process can be folded back together from the log
(docs/RESILIENCE.md §Router journal).

:data:`KNOWN_EVENTS` is the pinned registry of event kinds, exactly
like ``resilience.faults.KNOWN_SITES`` is for fault-injection sites:
the ``journal-coverage`` lint rule (docs/ANALYSIS.md) checks that
every ``journal.append("<kind>", ...)`` in the serving tier uses a
registered kind, that every registered kind is actually emitted
somewhere, and that every terminal request transition (a
``RequestResult`` construction, a ``results[...]`` store, a tick
transition marker) lives in a function that either journals or carries
a classified ``# tpu-lint: allow(journal-coverage)`` annotation. A new
transition added without an event is a recovery blind spot — the rule
makes it a lint failure instead of a chaos-soak surprise.
"""

import json
import logging
import os
import time
import zlib

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["KNOWN_EVENTS", "TRACE_ID_EVENTS", "ROUTER_JOURNAL_SCHEMA",
           "RouterJournal"]

ROUTER_JOURNAL_SCHEMA = "paddle_tpu.router_journal/v1"

#: The journal-event registry: every kind the serving tier may append,
#: with the transition it records. ``journal-coverage`` (tpu-lint) pins
#: emit sites against this dict and flags registered-but-never-emitted
#: kinds; docs/RESILIENCE.md renders it as the event table. Replay
#: folds events in this order: accept -> place -> progress -> finish.
KNOWN_EVENTS = {
    "header": "journal birth record: schema, replica count, router seed",
    "accept": "request accepted by the tier (prompt, seed, priority, "
              "deadline, trace_id, first placement) — the zero-loss "
              "contract AND the causal trace both start here",
    "place": "request (re-)placed onto a replica (trace_id carried): "
             "failover/drain re-placement and tier-level shed rescue",
    "progress": "periodic generated-so-far token mirror for unfinished "
                "requests (any prefix is a token-exact resume point)",
    "finish": "request reached a terminal state (eos/length/deadline/"
              "shed) with its tokens, trace_id and latency telemetry",
    "failover": "dead replica rebuilt (mode=restore|redistribute)",
    "drain": "replica elastically drained; its work migrated",
    "add_replica": "tier grew by one (warm-joined) replica slot",
    "close": "router closed cleanly (no recovery needed past here)",
    "recover": "router process rebuilt from this journal",
    "prefix_share": "tier prefix store copied finished prefill blocks "
                    "from one replica's cache into another's ahead of a "
                    "placement (block copy instead of recompute)",
    "migrate_blocks": "prefill→decode role migration shipped the "
                      "released request's finished KV blocks to the "
                      "decode side before re-placement",
}

#: request-scoped event kinds whose payload MUST carry ``trace_id`` —
#: the causal chain a request's journal events form across replicas
#: (docs/OBSERVABILITY.md has the trace_id lifecycle table;
#: ``timeline.verify_trace_continuity`` checks real journals against
#: it, and ``append`` warns on a violation at the write site).
TRACE_ID_EVENTS = frozenset({"accept", "place", "finish"})


class RouterJournal:
    """Append-only CRC-framed JSONL journal.

    Each line is ``{"crc": crc32(payload_str), "p": payload_str}`` where
    ``payload_str`` is the compact-JSON event — the crc is computed over
    the exact serialized bytes, so :meth:`replay` detects torn tails and
    bit-flips without re-serialization ambiguity. Corrupt lines are
    SKIPPED (counted under ``resilience.journal_corrupt_skipped``), not
    fatal: an append-only journal's last line is the only one a crash
    can tear, and one damaged line must not strand the recovery — the
    same walk-past philosophy as the snapshot manifests."""

    def __init__(self, path: str, retry_policy=None):
        from paddle_tpu.resilience.retry import RetryPolicy
        self.path = path
        self.retry_policy = retry_policy or RetryPolicy()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def append(self, kind: str, **fields) -> bool:
        """Durably append one event; returns False (and warns) when the
        sink stays broken past the retry budget — journal loss degrades
        router-crash durability, it must not reject live work. An
        unregistered ``kind`` warns (mirroring ``faults.arm`` on an
        unknown site) but still appends: durability first, registry
        hygiene is the lint rule's job."""
        from paddle_tpu.observability import registry
        from paddle_tpu.observability.registry import append_jsonl_lines
        from paddle_tpu.resilience.retry import call_with_retry

        if kind not in KNOWN_EVENTS:
            logger.warning(
                "journal event kind %r is not registered in "
                "serving.journal.KNOWN_EVENTS (known: %s) — replay "
                "tooling cannot see it", kind, ", ".join(KNOWN_EVENTS))
        elif kind in TRACE_ID_EVENTS and fields.get("trace_id") is None:
            logger.warning(
                "journal event %r appended without a trace_id — the "
                "request's causal chain breaks here "
                "(serving.journal.TRACE_ID_EVENTS)", kind)
        evt = {"kind": kind, "ts": round(time.time(), 6)}
        evt.update(fields)
        p = json.dumps(evt, separators=(",", ":"), sort_keys=True)
        line = json.dumps({"crc": zlib.crc32(p.encode()), "p": p},
                          separators=(",", ":"))
        try:
            call_with_retry(lambda: append_jsonl_lines(self.path, [line]),
                            policy=self.retry_policy,
                            retry_on=(OSError,),
                            describe="router.journal")
        except OSError:
            logger.warning("router journal append to %s failed past the "
                           "retry budget (kind=%s)", self.path, kind,
                           exc_info=True)
            return False
        registry().counter("serving.router.journal_events",
                           kind=kind).inc()
        return True

    @staticmethod
    def replay(path: str):
        """(events, corrupt_count): every intact event oldest-first.
        Unparseable or crc-failing lines (torn tail, bit rot) are
        skipped and counted — ``resilience.journal_corrupt_skipped``."""
        from paddle_tpu.resilience import record_event

        events, corrupt = [], 0
        if not os.path.isfile(path):
            return events, corrupt
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    outer = json.loads(ln)
                    p = outer["p"]
                    if zlib.crc32(p.encode()) != outer["crc"]:
                        raise ValueError("crc mismatch")
                    events.append(json.loads(p))
                except Exception:   # noqa: BLE001 — any damage = skip
                    corrupt += 1
                    record_event("journal_corrupt_skipped")
        return events, corrupt
