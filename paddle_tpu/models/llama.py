"""Llama family — the hybrid-parallel flagship (BASELINE configs #2).

Capability reference: PaddleNLP's Llama pretrain runs on the reference
substrate via Fleet hybrid parallel (SURVEY.md §2.7 note, §6 config matrix:
"Llama-2 7B/65B hybrid mp×pp×sharding-2").

TPU-first choices:
* attention + MLP built from the tensor-parallel layers (parallel/mp_layers):
  q/k/v/gate/up are column-parallel, o/down are row-parallel, the embedding is
  vocab-parallel — on a 1-device mesh they degrade to dense layers, so one
  implementation serves tests, single-chip and the full mesh.
* GQA (num_kv_heads < num_heads) with head counts divisible by the mp degree.
* RoPE via ops.rope (XLA fuses the rotation into the attention matmuls),
  RMSNorm via ops.rms_norm (Pallas on TPU), attention via
  F.scaled_dot_product_attention (Pallas flash path on TPU).
* weights default to the reference's init (normal(0, initializer_range)).
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.ops import rope as rope_ops
from paddle_tpu.parallel import mp_layers as mp


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None      # None → MHA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_base: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    # sequence-parallel activations between TP regions (Megatron-SP)
    sequence_parallel: bool = False
    # long-context strategy over the 'sep' mesh axis: None | 'ring' | 'ulysses'
    context_parallel: Optional[str] = None
    # per-layer activation recompute in the no-cache (training) forward
    recompute: bool = False
    # reference recompute_granularity (fleet recompute): what gets
    # RECOMPUTED in backward. 'full' = the whole layer (boundaries only
    # saved — max memory saving, ~fwd/3 extra FLOPs); 'full_attn' = the
    # attention block (projection/FFN matmul outputs saved); 'core_attn' =
    # only softmax(qk)v (q/k/v saved too — min recompute: the flash
    # kernel's fwd replay for its LSE residual is the only matmul re-run)
    recompute_granularity: str = "full"
    # train_loss(): compute the final norm→unembed→CE in this many
    # sequence chunks under remat (the full (b, s, vocab) logits tensor
    # never materializes); 1 = plain head+loss
    loss_seq_chunks: int = 1
    # Mistral-style causal sliding-window attention (None = full causal).
    # Rides the flash kernel's window_size support in training; the decode
    # path masks the KV cache to the last `sliding_window` positions.
    sliding_window: Optional[int] = None

    @classmethod
    def mistral_7b(cls):
        # 4096-key window over a 32k context (the published pairing — a
        # window equal to max positions would never mask anything)
        return cls(vocab_size=32000, hidden_size=4096,
                   intermediate_size=14336, num_layers=32, num_heads=32,
                   num_kv_heads=8, max_position_embeddings=32768,
                   sliding_window=4096)

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, vocab_size=256):
        return cls(vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
                   num_layers=2, num_heads=4, num_kv_heads=2,
                   max_position_embeddings=128)

    @classmethod
    def llama2_7b(cls):
        return cls()

    @classmethod
    def llama2_13b(cls):
        return cls(hidden_size=5120, intermediate_size=13824, num_layers=40,
                   num_heads=40)

    @classmethod
    def llama_65b(cls):
        """Llama-65B shape (BASELINE config #2 north-star scale)."""
        return cls(hidden_size=8192, intermediate_size=22016, num_layers=80,
                   num_heads=64, max_position_embeddings=2048)

    @classmethod
    def llama2_70b(cls):
        return cls(hidden_size=8192, intermediate_size=28672, num_layers=80,
                   num_heads=64, num_kv_heads=8)


def _tp_classes(cfg: LlamaConfig):
    """Column/row TP layer classes, SP variants when sequence_parallel."""
    if cfg.sequence_parallel:
        return mp.ColumnSequenceParallelLinear, mp.RowSequenceParallelLinear
    return mp.ColumnParallelLinear, mp.RowParallelLinear


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, nh, nkv, hd = (cfg.hidden_size, cfg.num_heads, cfg.kv_heads,
                          cfg.head_dim)
        w = init.Normal(0.0, cfg.initializer_range)
        col, row = _tp_classes(cfg)
        self.q_proj = col(h, nh * hd, weight_attr=w, has_bias=False,
                          gather_output=False)
        self.k_proj = col(h, nkv * hd, weight_attr=w, has_bias=False,
                          gather_output=False)
        self.v_proj = col(h, nkv * hd, weight_attr=w, has_bias=False,
                          gather_output=False)
        self.o_proj = row(nh * hd, h, weight_attr=init.Normal(
            0.0, cfg.initializer_range / math.sqrt(2 * cfg.num_layers)),
            has_bias=False, input_is_parallel=True)
        self.cfg = cfg

    def forward(self, x, cos=None, sin=None, attn_mask=None, cache=None,
                start_pos=0):
        cfg = self.cfg
        b, s, _ = x.shape
        q = self.q_proj(x).reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = self.k_proj(x).reshape(b, s, cfg.kv_heads, cfg.head_dim)
        v = self.v_proj(x).reshape(b, s, cfg.kv_heads, cfg.head_dim)
        if cos is None or sin is None:
            pos = start_pos + jnp.arange(s)
            cos, sin = rope_ops.rope_cos_sin(s, cfg.head_dim,
                                             base=cfg.rope_base,
                                             position_ids=pos)
        q = rope_ops.apply_rotary_pos_emb(q, cos, sin)
        k = rope_ops.apply_rotary_pos_emb(k, cos, sin)
        if cache is not None:
            # decode: write k/v at [start_pos, start_pos+s), attend to the
            # filled prefix (static max length, position-masked)
            import jax as _jax
            k_cache = _jax.lax.dynamic_update_slice_in_dim(
                cache["k"], k.astype(cache["k"].dtype), start_pos, axis=1)
            v_cache = _jax.lax.dynamic_update_slice_in_dim(
                cache["v"], v.astype(cache["v"].dtype), start_pos, axis=1)
            max_len = k_cache.shape[1]
            q_pos = start_pos + jnp.arange(s)[:, None]          # (s, 1)
            k_pos = jnp.arange(max_len)[None, :]                 # (1, max)
            mask = (k_pos <= q_pos)[None, None]                  # causal+fill
            if cfg.sliding_window is not None:
                mask = mask & (k_pos > q_pos - cfg.sliding_window)[None, None]
            out = F.scaled_dot_product_attention(
                q, k_cache, v_cache, attn_mask=mask, is_causal=False)
            out = self.o_proj(out.reshape(b, s, cfg.num_heads * cfg.head_dim))
            return out, {"k": k_cache, "v": v_cache}
        if cfg.context_parallel:
            if cfg.sliding_window is not None:
                raise ValueError(
                    "sliding_window is not supported on the "
                    "context_parallel path (the ring/Ulysses kernels "
                    "attend the full causal context) — silent full-causal "
                    "training would mismatch the windowed decode")
            from paddle_tpu.parallel.context_parallel import (
                context_parallel_attention)
            out = context_parallel_attention(q, k, v, axis="sep",
                                             mode=cfg.context_parallel)
        else:
            # named for the recompute_granularity save policies
            from jax.ad_checkpoint import checkpoint_name
            q = checkpoint_name(q, "attn_qkv")
            k = checkpoint_name(k, "attn_qkv")
            v = checkpoint_name(v, "attn_qkv")
            # always causal; an attn_mask (e.g. padding) composes with it
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=True,
                window_size=cfg.sliding_window)
            out = checkpoint_name(out, "attn_out")
        return self.o_proj(out.reshape(b, s, cfg.num_heads * cfg.head_dim))


class LlamaMLP(nn.Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, ffn = cfg.hidden_size, cfg.intermediate_size
        w = init.Normal(0.0, cfg.initializer_range)
        col, row = _tp_classes(cfg)
        self.gate_proj = col(h, ffn, weight_attr=w, has_bias=False,
                             gather_output=False)
        self.up_proj = col(h, ffn, weight_attr=w, has_bias=False,
                           gather_output=False)
        self.down_proj = row(ffn, h, weight_attr=init.Normal(
            0.0, cfg.initializer_range / math.sqrt(2 * cfg.num_layers)),
            has_bias=False, input_is_parallel=True)

    def forward(self, x):
        from jax.ad_checkpoint import checkpoint_name
        g = checkpoint_name(self.gate_proj(x), "ffn_gate")
        u = checkpoint_name(self.up_proj(x), "ffn_up")
        return self.down_proj(F.silu(g) * u)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   epsilon=cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, cos=None, sin=None, attn_mask=None, cache=None,
                start_pos=0):
        if cache is not None:
            attn, new_cache = self.self_attn(self.input_layernorm(x), cos,
                                             sin, attn_mask, cache=cache,
                                             start_pos=start_pos)
            x = x + attn
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        x = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = mp.VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            weight_attr=init.Normal(0.0, cfg.initializer_range))
        self.layers = nn.LayerList([LlamaDecoderLayer(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, cache=None, start_pos=0):
        cfg = self.cfg
        s = input_ids.shape[1]
        pos = start_pos + jnp.arange(s) if cache is not None else None
        cos, sin = rope_ops.rope_cos_sin(s, cfg.head_dim, base=cfg.rope_base,
                                         position_ids=pos)
        x = self.embed_tokens(input_ids)
        if cache is not None:
            new_cache = []
            for i, layer in enumerate(self.layers):
                x, c = layer(x, cos, sin, attn_mask, cache=cache[i],
                             start_pos=start_pos)
                new_cache.append(c)
            return self.norm(x), new_cache
        if cfg.recompute:
            # per-layer activation recompute (reference: fleet per-layer
            # recompute, fleet/meta_parallel recompute_hybrid). The
            # granularity maps to a named-save policy: 'full' saves only
            # layer boundaries; 'full_attn'/'core_attn' additionally save
            # the big matmul outputs so backward re-runs only the cheap
            # elementwise ops (+ the attention core for 'full_attn').
            from jax.ad_checkpoint import checkpoint_policies as cp
            gran = cfg.recompute_granularity
            # attn_out is deliberately NOT saved: the flash kernel's
            # backward replays its forward for the LSE residual anyway,
            # which reproduces the output — saving it would spend
            # b·s·h bytes/layer for nothing
            if gran == "full":
                policy = None
            elif gran == "full_attn":
                policy = cp.save_only_these_names("ffn_gate", "ffn_up")
            elif gran == "core_attn":
                policy = cp.save_only_these_names(
                    "attn_qkv", "ffn_gate", "ffn_up")
            else:
                raise ValueError(
                    f"unknown recompute_granularity {gran!r}; expected "
                    "'full', 'full_attn' or 'core_attn'")
            for layer in self.layers:
                x = jax.checkpoint(
                    lambda t, _l=layer: _l(t, cos, sin, attn_mask),
                    policy=policy)(x)
        else:
            for layer in self.layers:
                x = layer(x, cos, sin, attn_mask)
        return self.norm(x)


class CausalLMBase(nn.Layer):
    """Shared scaffolding for decoder-only LMs built on `.model` (with
    embed_tokens/layers/norm), `.lm_head` and `.loss_fn` attributes."""

    def init_cache(self, batch_size, max_len, dtype=jnp.bfloat16):
        """Preallocated KV cache: one {'k','v'} buffer pair per layer."""
        cfg = self.cfg
        shape = (batch_size, max_len, cfg.kv_heads, cfg.head_dim)
        return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
                for _ in range(cfg.num_layers)]

    def num_params(self):
        import numpy as np
        return sum(int(np.prod(p.shape)) for _, p in self.named_parameters())

    def train_loss(self, input_ids, labels, attn_mask=None):
        """Fused forward + LM loss. With ``cfg.loss_seq_chunks > 1`` the
        final norm→unembed→cross-entropy runs in sequence chunks under
        remat, so the full (b, s, vocab) logits tensor never exists — the
        TPU analog of the reference's fused head/loss kernels
        (fused_linear_param_grad_add + _c_softmax_with_cross_entropy):
        at 32k vocab the logits are the single largest training
        activation (0.5-1 GiB at b4 s2048), and chunking trades them for
        a per-chunk lm_head replay in backward (~1% of step FLOPs)."""
        chunks = getattr(self.cfg, "loss_seq_chunks", 1)
        x = self.model(input_ids, attn_mask)
        aux = jnp.zeros((), jnp.float32)
        if isinstance(x, tuple):      # MoE bodies return (hidden, aux)
            x, aux = x
            aux = getattr(self.cfg, "aux_loss_weight", 1.0) * aux
        if chunks <= 1:
            return self.loss_fn(self._unembed(x), labels,
                                reduction="mean") + aux
        b, s, h = x.shape
        if s % chunks:
            raise ValueError(
                f"loss_seq_chunks={chunks} does not divide seq {s}")
        sc = s // chunks
        xc = jnp.moveaxis(x.reshape(b, chunks, sc, h), 1, 0)
        lc = jnp.moveaxis(labels.reshape(b, chunks, sc), 1, 0)
        ignore = getattr(self.loss_fn, "ignore_index", -100)

        @jax.checkpoint
        def chunk_sums(x_c, l_c):
            nll = self.loss_fn(self._unembed(x_c), l_c, reduction="none")
            return jnp.sum(nll), jnp.sum(l_c != ignore)

        def body(carry, xs):
            loss_sum, cnt = carry
            a, n = chunk_sums(*xs)
            return (loss_sum + a, cnt + n), None

        (loss_sum, cnt), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
            (xc, lc))
        return loss_sum / jnp.maximum(cnt, 1) + aux

    def _unembed(self, x):
        if getattr(self.cfg, "tie_word_embeddings", False):
            from paddle_tpu.parallel import mp_layers as _mp
            logits = jnp.matmul(x, self.model.embed_tokens.weight.T)
            return _mp.constrain(logits, _mp._last_dim_spec(_mp.MP_AXIS))
        return self.lm_head(x)

    def _pipeline_block_apply(self, template):
        """(one_block_state, h) -> h, built over `template`. Subclasses with
        per-block extra losses return (h, extra) instead."""
        from paddle_tpu.nn.layer import functional_call
        cfg = self.cfg

        def block_apply(st, h):
            s = h.shape[1]
            cos, sin = rope_ops.rope_cos_sin(s, cfg.head_dim,
                                             base=cfg.rope_base)
            return functional_call(template, st, h, cos, sin, None)

        return block_apply

    def pipeline_parts(self):
        """Factor the model for the SPMD pipeline schedule
        (parallel.pipeline.make_pipeline_train_step). Tied embeddings ride
        the pipeline's tied_head path (SharedLayerDesc parity)."""
        from paddle_tpu.nn.layer import functional_call
        from paddle_tpu.parallel.pipeline import PipelineParts, part_specs

        tied = self.cfg.tie_word_embeddings
        embed = self.model.embed_tokens
        blocks = list(self.model.layers)
        template = blocks[0]
        block_apply = self._pipeline_block_apply(template)

        def embed_apply(st, ids):
            return functional_call(embed, st, ids)

        if tied:
            norm = self.model.norm
            loss_fn = self.loss_fn

            def head_apply(head_st, embed_st, h, labels):
                x = functional_call(norm, head_st, h)
                logits = jnp.matmul(x, embed_st["weight"].T)
                logits = mp.constrain(logits, mp._last_dim_spec(mp.MP_AXIS))
                return loss_fn(logits, labels, reduction="mean")

            head_state = norm.trainable_state()
            head_pspecs = part_specs(norm)
        else:
            head = _LMHead(self.model.norm, self.lm_head, self.loss_fn)

            def head_apply(st, h, labels):
                return functional_call(head, st, h, labels)

            head_state = head.trainable_state()
            head_pspecs = part_specs(head)

        return PipelineParts(
            embed_state=embed.trainable_state(),
            embed_apply=embed_apply,
            block_states=[b.trainable_state() for b in blocks],
            block_apply=block_apply,
            head_state=head_state,
            head_apply=head_apply,
            embed_pspecs=part_specs(embed),
            block_pspecs=part_specs(template),
            head_pspecs=head_pspecs,
            tied_head=tied,
        )


class LlamaForCausalLM(CausalLMBase):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if not cfg.tie_word_embeddings:
            # vocab-sharded logits stay sharded into the parallel loss —
            # never materialize a replicated (b, s, vocab) activation
            self.lm_head = mp.ColumnParallelLinear(
                cfg.hidden_size, cfg.vocab_size,
                weight_attr=init.Normal(0.0, cfg.initializer_range),
                has_bias=False, gather_output=False)
        self.loss_fn = mp.ParallelCrossEntropy()

    def forward(self, input_ids, attn_mask=None, cache=None, start_pos=0):
        if cache is not None:
            x, new_cache = self.model(input_ids, attn_mask, cache=cache,
                                      start_pos=start_pos)
            return self._unembed(x), new_cache
        x = self.model(input_ids, attn_mask)
        return self._unembed(x)    # _unembed: CausalLMBase

    def fused_decode_plan(self, state, probe=False):
        """Plan for the fused decode-step path (ops.fused_decode — the
        fused_multi_transformer analog): stacked per-layer weights plus
        embed/head closures, or None when this config can't ride it
        (active TP mesh, odd head_dim). Weight-only-int8 states build the
        int8 variant (fused_multi_transformer_int8 analog).

        With probe=True only eligibility + static meta are computed (no
        device work) — generate() probes before jit and builds the real
        plan from the traced state inside the jitted program."""
        from paddle_tpu.parallel.mp_layers import _active_mesh
        cfg = self.cfg
        if (_active_mesh(mp.MP_AXIS) is not None or cfg.head_dim % 2
                or cfg.sliding_window is not None):
            # sliding-window decode masks the cache; the fused kernel
            # attends the full filled prefix — scan path serves it
            return None
        int8 = "model.layers.0.self_attn.q_proj.weight_q" in state
        if not int8 and "model.layers.0.self_attn.q_proj.weight" not in state:
            return None     # non-standard state
        from paddle_tpu.ops import fused_decode as fd
        hd = cfg.head_dim
        dq = cfg.num_heads * hd
        blocks = fd.decode_block_plan(
            cfg.hidden_size, dq + 2 * cfg.kv_heads * hd, dq, hd,
            cfg.intermediate_size, wbytes=1 if int8 else 2)
        meta = {
            "num_heads": cfg.num_heads, "num_kv_heads": cfg.kv_heads,
            "head_dim": cfg.head_dim, "eps": cfg.rms_norm_eps,
            "rope_base": cfg.rope_base, "blocks": blocks,
        }
        if probe:
            return meta
        from paddle_tpu.ops.rms_norm import rms_norm
        params = fd.build_fused_params(state, cfg.num_layers,
                                       ffn_pad=blocks["ffn_pad"])
        embed_w = state["model.embed_tokens.weight"]
        norm_w = state["model.norm.weight"]

        def embed(tok, pos):                  # (b,), scalar -> (b, h)
            del pos                           # rope positions, not learned
            return jnp.take(embed_w, tok, axis=0)

        if cfg.tie_word_embeddings:
            from paddle_tpu.ops import tied_unembed
            head_mm = lambda xn: tied_unembed(xn, embed_w)
        elif int8 and "lm_head.weight_q" in state:
            from paddle_tpu.quantization import weight_only_linear
            head_mm = lambda xn: weight_only_linear(
                xn, state["lm_head.weight_q"], state["lm_head.weight_scale"])
        else:
            head_mm = lambda xn: jnp.dot(xn, state["lm_head.weight"])

        def head(x):                          # (b, h) -> (b, vocab)
            return head_mm(rms_norm(x, norm_w, cfg.rms_norm_eps))

        return dict(meta, params=params, embed=embed, head=head)

    def loss(self, logits, labels):
        # reduction='mean' divides by the count of non-ignored labels
        return self.loss_fn(logits, labels, reduction="mean")


class _LMHead(nn.Layer):
    """Final norm + unembedding + mean parallel-CE loss (pipeline tail)."""

    def __init__(self, norm, lm_head, loss_fn):
        super().__init__()
        self.norm = norm
        self.lm_head = lm_head
        self.loss_fn = loss_fn

    def forward(self, h, labels):
        logits = self.lm_head(self.norm(h))
        return self.loss_fn(logits, labels, reduction="mean")
