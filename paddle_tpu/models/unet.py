"""Stable-Diffusion UNet (BASELINE config #5: "conv+attn Phi fusion → Pallas").

Capability reference: ppdiffusers' UNet2DConditionModel rides the reference's
conv/fused-attention kernels (SURVEY.md §2.7 note). TPU-first: convs lower to
XLA's MXU conv path; the spatial/cross attention reuses
F.scaled_dot_product_attention (Pallas flash path on TPU); GroupNorm+SiLU
chains are XLA-fused.

Structure (SD 1.x): sinusoidal timestep embedding → MLP; down/up blocks of
[ResBlock, SpatialTransformer(self-attn + cross-attn to text context)] with
skip connections; NCHW layout like the reference.
"""

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn import functional as F


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    model_channels: int = 320
    channel_mult: Tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    attention_levels: Tuple[int, ...] = (0, 1, 2)   # levels with transformers
    num_heads: int = 8
    context_dim: Optional[int] = 768                 # None → self-attn only
    groups: int = 32

    @classmethod
    def sd15(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(in_channels=4, out_channels=4, model_channels=32,
                   channel_mult=(1, 2), num_res_blocks=1,
                   attention_levels=(1,), num_heads=4, context_dim=16,
                   groups=8)


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal embeddings (b,) → (b, dim)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class ResBlock(nn.Layer):
    def __init__(self, in_ch, out_ch, temb_ch, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(min(groups, in_ch), in_ch)
        self.conv1 = nn.Conv2D(in_ch, out_ch, 3, padding=1)
        self.temb_proj = nn.Linear(temb_ch, out_ch)
        self.norm2 = nn.GroupNorm(min(groups, out_ch), out_ch)
        self.conv2 = nn.Conv2D(out_ch, out_ch, 3, padding=1)
        self.skip = (nn.Conv2D(in_ch, out_ch, 1) if in_ch != out_ch
                     else nn.Identity())

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.temb_proj(F.silu(temb))[:, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        return self.skip(x) + h


class _CrossAttention(nn.Layer):
    def __init__(self, dim, ctx_dim, num_heads):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.to_q = nn.Linear(dim, dim, bias_attr=False)
        self.to_k = nn.Linear(ctx_dim, dim, bias_attr=False)
        self.to_v = nn.Linear(ctx_dim, dim, bias_attr=False)
        self.to_out = nn.Linear(dim, dim)

    def forward(self, x, ctx=None):
        ctx = x if ctx is None else ctx
        b, s, _ = x.shape
        sk = ctx.shape[1]
        q = self.to_q(x).reshape(b, s, self.num_heads, self.head_dim)
        k = self.to_k(ctx).reshape(b, sk, self.num_heads, self.head_dim)
        v = self.to_v(ctx).reshape(b, sk, self.num_heads, self.head_dim)
        out = F.scaled_dot_product_attention(q, k, v)
        return self.to_out(out.reshape(b, s, -1))


class _GEGLU(nn.Layer):
    def __init__(self, dim, inner):
        super().__init__()
        self.proj = nn.Linear(dim, inner * 2)
        self.out = nn.Linear(inner, dim)

    def forward(self, x):
        a, g = jnp.split(self.proj(x), 2, axis=-1)
        return self.out(a * F.gelu(g))


class SpatialTransformer(nn.Layer):
    """GN → 1x1 in → [self-attn, cross-attn, GEGLU-FF] → 1x1 out (+residual)."""

    def __init__(self, ch, num_heads, ctx_dim, groups):
        super().__init__()
        self.norm = nn.GroupNorm(min(groups, ch), ch)
        self.proj_in = nn.Conv2D(ch, ch, 1)
        self.norm1 = nn.LayerNorm(ch)
        self.attn1 = _CrossAttention(ch, ch, num_heads)
        self.norm2 = nn.LayerNorm(ch)
        self.attn2 = _CrossAttention(ch, ctx_dim if ctx_dim else ch, num_heads)
        self.norm3 = nn.LayerNorm(ch)
        self.ff = _GEGLU(ch, 4 * ch)
        self.proj_out = nn.Conv2D(ch, ch, 1)
        self.has_ctx = ctx_dim is not None

    def forward(self, x, ctx=None):
        b, c, h, w = x.shape
        res = x
        y = self.proj_in(self.norm(x))
        y = y.reshape(b, c, h * w).transpose(0, 2, 1)        # (b, hw, c)
        y = y + self.attn1(self.norm1(y))
        y = y + self.attn2(self.norm2(y), ctx if self.has_ctx else None)
        y = y + self.ff(self.norm3(y))
        y = y.transpose(0, 2, 1).reshape(b, c, h, w)
        return res + self.proj_out(y)


class Downsample(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.op = nn.Conv2D(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.op(x)


class Upsample(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2D(ch, ch, 3, padding=1)

    def forward(self, x):
        x = F.interpolate(x, scale_factor=2, mode="nearest",
                          data_format="NCHW")
        return self.conv(x)


class UNetModel(nn.Layer):
    def __init__(self, cfg: UNetConfig):
        super().__init__()
        self.cfg = cfg
        mc = cfg.model_channels
        temb_ch = mc * 4
        self.time_mlp1 = nn.Linear(mc, temb_ch)
        self.time_mlp2 = nn.Linear(temb_ch, temb_ch)
        self.conv_in = nn.Conv2D(cfg.in_channels, mc, 3, padding=1)

        chans = [mc]
        ch = mc
        self.down_blocks = nn.LayerList()
        self.down_attns = nn.LayerList()
        self.downsamplers = nn.LayerList()
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = mc * mult
            for _ in range(cfg.num_res_blocks):
                self.down_blocks.append(ResBlock(ch, out_ch, temb_ch,
                                                 cfg.groups))
                ch = out_ch
                self.down_attns.append(
                    SpatialTransformer(ch, cfg.num_heads, cfg.context_dim,
                                       cfg.groups)
                    if level in cfg.attention_levels else nn.Identity())
                chans.append(ch)
            if level != len(cfg.channel_mult) - 1:
                self.downsamplers.append(Downsample(ch))
                chans.append(ch)
            else:
                self.downsamplers.append(nn.Identity())

        self.mid_block1 = ResBlock(ch, ch, temb_ch, cfg.groups)
        self.mid_attn = SpatialTransformer(ch, cfg.num_heads, cfg.context_dim,
                                           cfg.groups)
        self.mid_block2 = ResBlock(ch, ch, temb_ch, cfg.groups)

        self.up_blocks = nn.LayerList()
        self.up_attns = nn.LayerList()
        self.upsamplers = nn.LayerList()
        for level, mult in reversed(list(enumerate(cfg.channel_mult))):
            out_ch = mc * mult
            for i in range(cfg.num_res_blocks + 1):
                skip = chans.pop()
                self.up_blocks.append(ResBlock(ch + skip, out_ch, temb_ch,
                                               cfg.groups))
                ch = out_ch
                self.up_attns.append(
                    SpatialTransformer(ch, cfg.num_heads, cfg.context_dim,
                                       cfg.groups)
                    if level in cfg.attention_levels else nn.Identity())
            if level != 0:
                self.upsamplers.append(Upsample(ch))
            else:
                self.upsamplers.append(nn.Identity())

        self.norm_out = nn.GroupNorm(min(cfg.groups, ch), ch)
        self.conv_out = nn.Conv2D(ch, cfg.out_channels, 3, padding=1)

    def forward(self, x, timesteps, context=None):
        cfg = self.cfg
        temb = timestep_embedding(timesteps, cfg.model_channels)
        # the sinusoidal table is fp32; follow the model's compute dtype
        # (bf16 inference would otherwise poison the conv inputs to fp32)
        temb = temb.astype(self.time_mlp1.weight.dtype)
        temb = self.time_mlp2(F.silu(self.time_mlp1(temb)))

        h = self.conv_in(x)
        skips = [h]
        bi = 0
        for level in range(len(cfg.channel_mult)):
            for _ in range(cfg.num_res_blocks):
                h = self.down_blocks[bi](h, temb)
                attn = self.down_attns[bi]
                h = attn(h, context) if isinstance(
                    attn, SpatialTransformer) else attn(h)
                skips.append(h)
                bi += 1
            ds = self.downsamplers[level]
            if not isinstance(ds, nn.Identity):
                h = ds(h)
                skips.append(h)

        h = self.mid_block1(h, temb)
        h = self.mid_attn(h, context)
        h = self.mid_block2(h, temb)

        bi = 0
        for li, level in enumerate(reversed(range(len(cfg.channel_mult)))):
            for _ in range(cfg.num_res_blocks + 1):
                h = jnp.concatenate([h, skips.pop()], axis=1)
                h = self.up_blocks[bi](h, temb)
                attn = self.up_attns[bi]
                h = attn(h, context) if isinstance(
                    attn, SpatialTransformer) else attn(h)
                bi += 1
            us = self.upsamplers[li]
            if not isinstance(us, nn.Identity):
                h = us(h)

        return self.conv_out(F.silu(self.norm_out(h)))

    def num_params(self):
        import numpy as np
        return sum(int(np.prod(p.shape)) for _, p in self.named_parameters())


def ddpm_loss(model_or_state, model, x0, t, noise, context=None,
              alphas_cumprod=None):
    """ε-prediction MSE (the SD pretrain objective)."""
    import jax
    from paddle_tpu.nn.layer import functional_call
    a = alphas_cumprod[t][:, None, None, None]
    xt = jnp.sqrt(a) * x0 + jnp.sqrt(1.0 - a) * noise
    if isinstance(model_or_state, dict):
        eps = functional_call(model, model_or_state, xt, t, context)
    else:
        eps = model(xt, t, context)
    return jnp.mean((eps - noise) ** 2)


def cosine_alphas_cumprod(T=1000, s=0.008):
    t = jnp.arange(T + 1, dtype=jnp.float32) / T
    f = jnp.cos((t + s) / (1 + s) * math.pi / 2) ** 2
    return jnp.clip(f[1:] / f[0], 1e-5, 1.0)
