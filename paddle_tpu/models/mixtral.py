"""Mixtral / DeepSeekMoE — expert-parallel configs (BASELINE config #4).

Capability reference: "DeepSeekMoE / Mixtral (Fleet expert-parallel
alltoall)" rides the reference's MoELayer + global_scatter/global_gather
stack (SURVEY.md §2.6-EP); the models themselves live in PaddleNLP.

Architecture: Llama decoder (GQA attention, RMSNorm, RoPE) with the FFN
replaced by a token-choice MoE (nn.layers.moe.MoELayer); DeepSeekMoE-style
shared experts (always-on SwiGLU alongside the routed experts) optional.
Forward returns (logits, aux_loss) — the load-balance aux must reach the
task loss, including through the pipeline schedule (block_apply returns the
weighted aux per block).
"""

import dataclasses
from typing import Optional

import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn.layers.moe import MoELayer
from paddle_tpu.ops import rope as rope_ops
from paddle_tpu.parallel import mp_layers as mp
from paddle_tpu.models.llama import (
    CausalLMBase,
    LlamaConfig,
    LlamaAttention,
    LlamaMLP,
)


@dataclasses.dataclass
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    num_shared_experts: int = 0       # DeepSeekMoE: always-on experts
    moe_gate: str = "gshard"          # 'gshard' (top-k) | 'switch' (top-1)
    moe_dispatch: str = "scatter"     # 'scatter'|'sort'|'fused'|'einsum'
                                      # |'alltoall'
    moe_dropless: bool = False        # sort + ragged_dot, no capacity drops
    ep_axes: tuple = ("dp",)          # mesh axes the expert dim shards over

    @classmethod
    def tiny(cls, vocab_size=256):
        return cls(vocab_size=vocab_size, hidden_size=64, intermediate_size=96,
                   num_layers=2, num_heads=4, num_kv_heads=2,
                   max_position_embeddings=128, num_experts=4, top_k=2)

    @classmethod
    def mixtral_8x7b(cls):
        return cls(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                   num_layers=32, num_heads=32, num_kv_heads=8,
                   num_experts=8, top_k=2)

    @classmethod
    def deepseek_moe_16b(cls):
        # fine-grained experts + 2 shared (DeepSeekMoE scheme)
        return cls(vocab_size=102400, hidden_size=2048, intermediate_size=1408,
                   num_layers=28, num_heads=16, num_experts=64, top_k=6,
                   num_shared_experts=2)


class MixtralDecoderLayer(nn.Layer):
    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                          epsilon=cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   epsilon=cfg.rms_norm_eps)
        self.moe = MoELayer(cfg.hidden_size, cfg.intermediate_size,
                            cfg.num_experts, top_k=cfg.top_k,
                            capacity_factor=cfg.capacity_factor,
                            gate=cfg.moe_gate,
                            initializer_range=cfg.initializer_range,
                            dispatch_mode=cfg.moe_dispatch,
                            dropless=cfg.moe_dropless,
                            ep_axes=cfg.ep_axes)
        if cfg.num_shared_experts:
            shared_cfg = dataclasses.replace(
                cfg, intermediate_size=cfg.intermediate_size
                * cfg.num_shared_experts)
            self.shared_mlp = LlamaMLP(shared_cfg)
        self.cfg = cfg

    def forward(self, x, cos=None, sin=None, attn_mask=None, cache=None,
                start_pos=0):
        if cache is not None:
            attn, new_cache = self.self_attn(self.input_layernorm(x), cos,
                                             sin, attn_mask, cache=cache,
                                             start_pos=start_pos)
            x = x + attn
        else:
            new_cache = None
            x = x + self.self_attn(self.input_layernorm(x), cos, sin,
                                   attn_mask)
        h = self.post_attention_layernorm(x)
        moe_out, aux = self.moe(h)
        if self.cfg.num_shared_experts:
            moe_out = moe_out + self.shared_mlp(h)
        out = x + moe_out
        if cache is not None:
            return (out, aux), new_cache
        return out, aux


class MixtralModel(nn.Layer):
    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        self.cfg = cfg
        from paddle_tpu.nn import initializer as init
        self.embed_tokens = mp.VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            weight_attr=init.Normal(0.0, cfg.initializer_range))
        self.layers = nn.LayerList([MixtralDecoderLayer(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, cache=None, start_pos=0):
        cfg = self.cfg
        s = input_ids.shape[1]
        pos = start_pos + jnp.arange(s) if cache is not None else None
        cos, sin = rope_ops.rope_cos_sin(s, cfg.head_dim, base=cfg.rope_base,
                                         position_ids=pos)
        x = self.embed_tokens(input_ids)
        aux_total = jnp.zeros((), jnp.float32)
        if cache is not None:
            new_cache = []
            for i, layer in enumerate(self.layers):
                (x, aux), c = layer(x, cos, sin, attn_mask, cache=cache[i],
                                    start_pos=start_pos)
                aux_total = aux_total + aux
                new_cache.append(c)
            return (self.norm(x), aux_total), new_cache
        for layer in self.layers:
            x, aux = layer(x, cos, sin, attn_mask)
            aux_total = aux_total + aux
        return self.norm(x), aux_total


class MixtralForCausalLM(CausalLMBase):
    """Forward returns (logits, weighted_aux); loss() adds them."""

    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        if cfg.tie_word_embeddings:
            raise ValueError(
                "MixtralForCausalLM does not support tie_word_embeddings")
        self.cfg = cfg
        self.model = MixtralModel(cfg)
        from paddle_tpu.nn import initializer as init
        self.lm_head = mp.ColumnParallelLinear(
            cfg.hidden_size, cfg.vocab_size,
            weight_attr=init.Normal(0.0, cfg.initializer_range),
            has_bias=False, gather_output=False)
        self.loss_fn = mp.ParallelCrossEntropy()

    def forward(self, input_ids, attn_mask=None, cache=None, start_pos=0):
        if cache is not None:
            (x, aux), new_cache = self.model(input_ids, attn_mask,
                                             cache=cache, start_pos=start_pos)
            # decode path: logits only (generate's contract)
            return self.lm_head(x), new_cache
        x, aux = self.model(input_ids, attn_mask)
        return self.lm_head(x), self.cfg.aux_loss_weight * aux

    def loss(self, outputs, labels):
        logits, aux = outputs
        return self.loss_fn(logits, labels, reduction="mean") + aux

    def fused_decode_plan(self, state, probe=False):
        """Fused MoE decode plan (ops.fused_decode arch="moe" — the
        reference's fused MoE inference analog: fused_multi_transformer +
        global_scatter). Eligibility: no active TP mesh, even head_dim,
        E % 8 == 0 (gate-weight sublane alignment), standard dispatch.
        DeepSeekMoE shared experts ride the kernel as a dense SwiGLU
        streamed like the llama FFN (the model already concatenates them
        into one shared_mlp). `max_batch` bounds b so the per-expert load
        never exceeds routing capacity: a token's top-k experts are
        DISTINCT, so the worst case is all b tokens picking the same
        expert — load b, not b·top_k (this admits deepseek_moe_16b's
        k=6 and doubles the mixtral bound)."""
        from paddle_tpu.parallel.mp_layers import _active_mesh
        from paddle_tpu.parallel import mp_layers as mp_mod
        cfg = self.cfg
        if (_active_mesh(mp_mod.MP_AXIS) is not None or cfg.head_dim % 2
                or cfg.num_experts % 8
                or cfg.moe_dropless or cfg.sliding_window is not None):
            # sliding-window decode masks the cache; the fused kernel
            # attends the full filled prefix — scan path serves it
            return None
        if "model.layers.0.self_attn.q_proj.weight" not in state:
            return None     # non-standard / quantized state
        gate = self.model.layers[0].moe.gate
        max_batch = 0
        for b in range(1, 65):
            if b <= gate.capacity(b):
                max_batch = b
            else:
                break
        if max_batch == 0:
            return None
        from paddle_tpu.ops import fused_decode as fd
        hd = cfg.head_dim
        dq = cfg.num_heads * hd
        # decode_block_plan records cache_wbytes for the kernel's chunk
        # sizing + consistency assert; the MoE kernel plans its own
        # expert blocks, so the qkv/ffn split fields are informational
        blocks = fd.decode_block_plan(
            cfg.hidden_size, dq + 2 * cfg.kv_heads * hd, dq, hd,
            cfg.intermediate_size, wbytes=2)
        meta = {
            "num_heads": cfg.num_heads, "num_kv_heads": cfg.kv_heads,
            "head_dim": cfg.head_dim, "eps": cfg.rms_norm_eps,
            "rope_base": cfg.rope_base, "arch": "moe",
            "top_k": gate.top_k, "max_batch": max_batch,
            "blocks": blocks,
        }
        if probe:
            return meta
        from paddle_tpu.ops import fused_decode as fd
        from paddle_tpu.ops.rms_norm import rms_norm
        params = fd.build_fused_params_moe(state, cfg.num_layers)
        embed_w = state["model.embed_tokens.weight"]
        norm_w = state["model.norm.weight"]
        head_w = state["lm_head.weight"]

        def embed(tok, pos):
            del pos
            return jnp.take(embed_w, tok, axis=0)

        def head(x):
            return jnp.dot(rms_norm(x, norm_w, cfg.rms_norm_eps), head_w)

        return dict(meta, params=params, embed=embed, head=head)

    def _pipeline_block_apply(self, template):
        from paddle_tpu.nn.layer import functional_call
        cfg = self.cfg

        def block_apply(st, h):
            s = h.shape[1]
            cos, sin = rope_ops.rope_cos_sin(s, cfg.head_dim,
                                             base=cfg.rope_base)
            h2, aux = functional_call(template, st, h, cos, sin, None)
            return h2, cfg.aux_loss_weight * aux

        return block_apply
