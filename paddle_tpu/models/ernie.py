"""ERNIE 3.0 (Titan-style) — semi-auto parallel config (BASELINE config #3).

Capability reference: ERNIE-3.0's unified pretraining splits a big shared
"universal representation" transformer from thin task-specific modules (NLU
masked-LM with bidirectional attention; NLG causal) — trained on the
reference substrate via the auto_parallel engine (SURVEY.md §6 configs).

This implementation: a bidirectional encoder backbone built from the TP
layers + a causal NLG branch sharing the backbone, masked-LM and causal-LM
losses. Run it under parallel.auto.Engine on a TPU mesh — the semi-auto
path (shard_tensor placements + GSPMD propagation) is exactly what the
reference's Completer/Partitioner/Resharder pipeline produces."""

import dataclasses
from typing import Optional

import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.parallel import mp_layers as mp


@dataclasses.dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12        # universal representation depth
    num_task_layers: int = 2           # task-specific (NLU/NLG) depth
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 4
    hidden_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02

    @classmethod
    def tiny(cls, vocab_size=256):
        return cls(vocab_size=vocab_size, hidden_size=64,
                   num_hidden_layers=2, num_task_layers=1, num_heads=4,
                   intermediate_size=128, max_position_embeddings=64,
                   hidden_dropout_prob=0.0)

    @classmethod
    def ernie3_titan(cls):
        # 260B-class: 48 shared + 12 task layers, hidden 12288 (paper scale)
        return cls(vocab_size=40000, hidden_size=12288,
                   num_hidden_layers=48, num_task_layers=12, num_heads=96,
                   intermediate_size=49152, max_position_embeddings=2048)


class ErnieSelfAttention(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        h = cfg.hidden_size
        w = init.Normal(0.0, cfg.initializer_range)
        self.qkv = mp.ColumnParallelLinear(h, 3 * h, weight_attr=w,
                                           gather_output=False)
        self.out = mp.RowParallelLinear(h, h, weight_attr=w,
                                        input_is_parallel=True)
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads

    def forward(self, x, attn_mask=None, causal=False):
        b, s, h = x.shape
        qkv = self.qkv(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.num_heads, self.head_dim)
        v = v.reshape(b, s, self.num_heads, self.head_dim)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             is_causal=causal)
        return self.out(out.reshape(b, s, h))


class ErnieLayer(nn.Layer):
    """Post-norm encoder block (BERT/ERNIE convention)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        h = cfg.hidden_size
        w = init.Normal(0.0, cfg.initializer_range)
        self.attn = ErnieSelfAttention(cfg)
        self.norm1 = nn.LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.fc1 = mp.ColumnParallelLinear(h, cfg.intermediate_size,
                                           weight_attr=w, gather_output=False)
        self.fc2 = mp.RowParallelLinear(cfg.intermediate_size, h,
                                        weight_attr=w, input_is_parallel=True)
        self.norm2 = nn.LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_mask=None, causal=False):
        x = self.norm1(x + self.dropout(self.attn(x, attn_mask, causal)))
        x = self.norm2(x + self.dropout(self.fc2(F.gelu(self.fc1(x)))))
        return x


class ErnieModel(nn.Layer):
    """Shared universal-representation backbone."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        w = init.Normal(0.0, cfg.initializer_range)
        self.word_emb = mp.VocabParallelEmbedding(cfg.vocab_size,
                                                  cfg.hidden_size,
                                                  weight_attr=w)
        self.pos_emb = nn.Embedding(cfg.max_position_embeddings,
                                    cfg.hidden_size, weight_attr=w)
        self.type_emb = nn.Embedding(cfg.type_vocab_size, cfg.hidden_size,
                                     weight_attr=w)
        self.emb_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.layers = nn.LayerList([ErnieLayer(cfg)
                                    for _ in range(cfg.num_hidden_layers)])

    def forward(self, input_ids, token_type_ids=None, attn_mask=None,
                causal=False):
        b, s = input_ids.shape
        pos = jnp.arange(s)[None, :]
        x = self.word_emb(input_ids) + self.pos_emb(pos)
        if token_type_ids is not None:
            x = x + self.type_emb(token_type_ids)
        x = self.dropout(self.emb_norm(x))
        for layer in self.layers:
            x = layer(x, attn_mask, causal)
        return x


class ErnieForPretraining(nn.Layer):
    """NLU branch (bidirectional masked-LM) + NLG branch (causal LM), both
    over the shared backbone — the ERNIE 3.0 task split."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.nlu_layers = nn.LayerList([ErnieLayer(cfg)
                                        for _ in range(cfg.num_task_layers)])
        self.nlg_layers = nn.LayerList([ErnieLayer(cfg)
                                        for _ in range(cfg.num_task_layers)])
        w = init.Normal(0.0, cfg.initializer_range)
        self.mlm_head = mp.ColumnParallelLinear(
            cfg.hidden_size, cfg.vocab_size, weight_attr=w, has_bias=False,
            gather_output=False)
        self.loss_fn = mp.ParallelCrossEntropy()

    def forward(self, input_ids, token_type_ids=None, branch="nlu"):
        causal = branch == "nlg"
        x = self.ernie(input_ids, token_type_ids, causal=causal)
        task_layers = self.nlg_layers if causal else self.nlu_layers
        for layer in task_layers:
            x = layer(x, causal=causal)
        return self.mlm_head(x)

    def loss(self, logits, labels):
        """labels: ignore_index=-100 marks unmasked positions (MLM) or
        padding (NLG)."""
        return self.loss_fn(logits, labels, reduction="mean")

    def num_params(self):
        import numpy as np
        return sum(int(np.prod(p.shape)) for _, p in self.named_parameters())
