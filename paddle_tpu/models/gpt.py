"""GPT-2 — the single-device end-to-end config (BASELINE config #1).

Capability reference: PaddleNLP's GPT pretrain on the reference substrate
(SURVEY.md §2.7 note). TPU-first choices: pre-norm blocks in bf16-friendly
form, attention through ops.flash_attention (MXU path), learned positional
embeddings, weight-tied unembedding.
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    max_position_embeddings: int = 1024
    intermediate_size: Optional[int] = None
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True

    # gpt2-345m preset
    @property
    def num_kv_heads(self):
        """MHA: kv heads == heads (llama-shaped accessors for shared
        roofline/cache math)."""
        return self.num_heads

    @classmethod
    def gpt2_medium(cls):
        return cls(hidden_size=1024, num_layers=24, num_heads=16)

    @classmethod
    def tiny(cls, vocab_size=1024):
        return cls(vocab_size=vocab_size, hidden_size=128, num_layers=2,
                   num_heads=4, max_position_embeddings=128,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0)

    @property
    def ffn_size(self):
        return self.intermediate_size or 4 * self.hidden_size


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h, nh = cfg.hidden_size, cfg.num_heads
        w_init = init.Normal(0.0, cfg.initializer_range)
        self.qkv_proj = nn.Linear(h, 3 * h, weight_attr=w_init)
        self.out_proj = nn.Linear(h, h, weight_attr=init.Normal(
            0.0, cfg.initializer_range / math.sqrt(2 * cfg.num_layers)))
        self.num_heads = nh
        self.head_dim = h // nh
        self.attn_dropout = cfg.attention_dropout_prob

    def forward(self, x, cache=None, start_pos=0):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.num_heads, self.head_dim)
        v = v.reshape(b, s, self.num_heads, self.head_dim)
        if cache is not None:
            # decode: append at [start_pos, start_pos+s), attend the
            # filled prefix (position-masked static buffers)
            k_cache = jax.lax.dynamic_update_slice_in_dim(
                cache["k"], k.astype(cache["k"].dtype), start_pos, axis=1)
            v_cache = jax.lax.dynamic_update_slice_in_dim(
                cache["v"], v.astype(cache["v"].dtype), start_pos, axis=1)
            q_pos = start_pos + jnp.arange(s)[:, None]
            k_pos = jnp.arange(k_cache.shape[1])[None, :]
            mask = (k_pos <= q_pos)[None, None]
            out = F.scaled_dot_product_attention(
                q, k_cache, v_cache, attn_mask=mask, is_causal=False)
            out = self.out_proj(out.reshape(b, s, h))
            return out, {"k": k_cache, "v": v_cache}
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=self.attn_dropout,
            training=self.training)
        return self.out_proj(out.reshape(b, s, h))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.attn = GPTAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        w_init = init.Normal(0.0, cfg.initializer_range)
        self.fc_in = nn.Linear(cfg.hidden_size, cfg.ffn_size, weight_attr=w_init)
        self.fc_out = nn.Linear(cfg.ffn_size, cfg.hidden_size,
                                weight_attr=init.Normal(
                                    0.0, cfg.initializer_range / math.sqrt(2 * cfg.num_layers)))
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, cache=None, start_pos=0):
        if cache is not None:
            attn, new_cache = self.attn(self.ln_1(x), cache=cache,
                                        start_pos=start_pos)
            x = x + attn
            x = x + self.fc_out(F.gelu(self.fc_in(self.ln_2(x)),
                                       approximate=True))
            return x, new_cache
        x = x + self.dropout(self.attn(self.ln_1(x)))
        x = x + self.dropout(self.fc_out(F.gelu(self.fc_in(self.ln_2(x)),
                                                approximate=True)))
        return x


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        w_init = init.Normal(0.0, cfg.initializer_range)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size, weight_attr=w_init)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                                weight_attr=w_init)
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)
        self.h = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)

    def forward(self, input_ids, cache=None, start_pos=0):
        b, s = input_ids.shape
        pos = (start_pos + jnp.arange(s))[None, :]
        x = self.wte(input_ids) + self.wpe(pos)
        if cache is not None:
            new_cache = []
            for i, block in enumerate(self.h):
                x, c = block(x, cache=cache[i], start_pos=start_pos)
                new_cache.append(c)
            return self.ln_f(x), new_cache
        x = self.drop(x)
        for block in self.h:
            x = block(x)
        return self.ln_f(x)


class GPTPretrainModel(nn.Layer):
    """LM head (tied) + causal LM loss."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)
        self.cfg = cfg
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, cache=None, start_pos=0):
        if cache is not None:
            x, new_cache = self.gpt(input_ids, cache=cache,
                                    start_pos=start_pos)
        else:
            x = self.gpt(input_ids)
        if self.cfg.tie_word_embeddings:
            logits = jnp.matmul(x, self.gpt.wte.weight.T)
        else:
            logits = self.lm_head(x)
        if cache is not None:
            return logits, new_cache
        return logits

    def init_cache(self, batch_size, max_len, dtype=jnp.bfloat16):
        cfg = self.cfg
        shape = (batch_size, max_len, cfg.num_heads,
                 cfg.hidden_size // cfg.num_heads)
        return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
                for _ in range(cfg.num_layers)]

    def fused_decode_plan(self, state, probe=False):
        """Fused decode-step plan, GPT block variant (ops.fused_decode
        arch='gpt' — LayerNorm+bias, MHA, learned positions, GELU): the
        architecture the reference's fused_multi_transformer serves."""
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_heads
        if hd % 2 or "gpt.h.0.attn.qkv_proj.weight" not in state:
            return None
        meta = {
            "num_heads": cfg.num_heads, "num_kv_heads": cfg.num_heads,
            "head_dim": hd, "eps": cfg.layer_norm_epsilon,
            "rope_base": 10000.0, "arch": "gpt",
        }
        if probe:
            return meta
        from paddle_tpu.ops import fused_decode as fd
        from paddle_tpu.nn.functional import layer_norm as _ln
        params = fd.build_fused_params_gpt(state, cfg.num_layers)
        wte = state["gpt.wte.weight"]
        wpe = state["gpt.wpe.weight"]
        lnf_w = state["gpt.ln_f.weight"]
        lnf_b = state["gpt.ln_f.bias"]
        def embed(tok, pos):                  # (b,), scalar -> (b, h)
            return jnp.take(wte, tok, axis=0) + wpe[pos]

        def head(x):
            xn = _ln(x, (x.shape[-1],), lnf_w, lnf_b,
                     cfg.layer_norm_epsilon)
            if cfg.tie_word_embeddings:
                from paddle_tpu.ops import tied_unembed
                return tied_unembed(xn, wte)
            return jnp.dot(xn, state["lm_head.weight"])

        return dict(meta, params=params, embed=embed, head=head)

    def loss(self, logits, labels):
        return F.cross_entropy(logits.reshape(-1, logits.shape[-1]),
                               labels.reshape(-1))

    def num_params(self):
        import numpy as np
        return sum(int(np.prod(p.shape)) for _, p in self.named_parameters())

    def pipeline_parts(self):
        """Factor for the SPMD pipeline (parallel.pipeline). Tied embeddings
        use the pipeline's tied_head path (SharedLayerDesc parity): the head
        unembeds with the embed stage's wte weight."""
        from paddle_tpu.nn.layer import functional_call
        from paddle_tpu.parallel.pipeline import PipelineParts, part_specs

        tied = self.cfg.tie_word_embeddings
        embed = _GPTEmbed(self.gpt.wte, self.gpt.wpe, self.gpt.drop)
        blocks = list(self.gpt.h)
        template = blocks[0]
        ln_f = self.gpt.ln_f
        model_loss = self.loss

        def embed_apply(st, ids):
            return functional_call(embed, st, ids)

        def block_apply(st, h):
            return functional_call(template, st, h)

        if tied:
            def head_apply(head_st, embed_st, h, labels):
                x = functional_call(ln_f, head_st, h)
                logits = jnp.matmul(x, embed_st["wte.weight"].T)
                return model_loss(logits, labels)

            head_state = ln_f.trainable_state()
            head_pspecs = part_specs(ln_f)
        else:
            head = _GPTHead(ln_f, self.lm_head, model_loss)

            def head_apply(st, h, labels):
                return functional_call(head, st, h, labels)

            head_state = head.trainable_state()
            head_pspecs = part_specs(head)

        return PipelineParts(
            embed_state=embed.trainable_state(),
            embed_apply=embed_apply,
            block_states=[b.trainable_state() for b in blocks],
            block_apply=block_apply,
            head_state=head_state,
            head_apply=head_apply,
            embed_pspecs=part_specs(embed),
            block_pspecs=part_specs(template),
            head_pspecs=head_pspecs,
            tied_head=tied,
        )


class _GPTEmbed(nn.Layer):
    def __init__(self, wte, wpe, drop):
        super().__init__()
        self.wte, self.wpe, self.drop = wte, wpe, drop

    def forward(self, ids):
        pos = jnp.arange(ids.shape[1])[None, :]
        return self.drop(self.wte(ids) + self.wpe(pos))


class _GPTHead(nn.Layer):
    def __init__(self, ln_f, lm_head, loss_fn):
        super().__init__()
        self.ln_f, self.lm_head = ln_f, lm_head
        self.loss_fn = loss_fn       # the model's own .loss — one definition

    def forward(self, h, labels):
        return self.loss_fn(self.lm_head(self.ln_f(h)), labels)
