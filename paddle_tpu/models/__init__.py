"""In-repo model zoo (BASELINE.json configs).

The reference keeps GPT/Llama/ERNIE/MoE/UNet in PaddleNLP/PaddleMIX; this repo
supplies minimal pretrain-grade implementations as the config matrix demands:
GPT-2 (345M single-device), Llama-2 (7B/65B hybrid), Mixtral-style MoE
(expert parallel), SD UNet (conv+attn).
"""

from paddle_tpu.models.gpt import GPTConfig, GPTModel, GPTPretrainModel  # noqa: F401
from paddle_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    LlamaModel,
    LlamaForCausalLM,
)
from paddle_tpu.models.mixtral import (  # noqa: F401
    MixtralConfig,
    MixtralModel,
    MixtralForCausalLM,
)
from paddle_tpu.models.ernie import (  # noqa: F401
    ErnieConfig,
    ErnieModel,
    ErnieForPretraining,
)
from paddle_tpu.models.unet import UNetConfig, UNetModel  # noqa: F401
