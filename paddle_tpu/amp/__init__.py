"""AMP — auto-mixed precision (ref: python/paddle/amp/ — auto_cast, decorate,
GradScaler; C++ eager autocast paddle/fluid/eager/amp_utils.h).

TPU-first: bf16 is the native MXU dtype and needs no loss scaling, so the
production path is O2-style — params cast to bf16, fp32 masters in the
optimizer (`multi_precision=True`), fp32 accumulation in matmuls/softmax
(handled inside our ops via preferred_element_type / explicit fp32 math).

* ``auto_cast(enable, dtype)``: context manager setting the compute-dtype
  policy; `amp_cast` consults it (O1-style per-op casting).
* ``decorate(models, optimizers, level='O2', dtype='bfloat16')``: casts model
  params; optimizer keeps fp32 masters.
* ``GradScaler``: dynamic loss scaling for fp16 parity; with bf16 it is an
  identity (matching the reference, which skips scaling for bf16).
"""

import contextlib
import threading

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtype import to_jax_dtype, is_floating

_tls = threading.local()

# ops whitelisted to run in low precision under O1 (mirrors the reference's
# white/black lists: matmul/conv in low precision, softmax/norm/reduce in fp32)
WHITE_LIST = {"matmul", "linear", "conv2d", "einsum", "bmm"}
BLACK_LIST = {"softmax", "log_softmax", "layer_norm", "rms_norm", "cross_entropy",
              "mean", "sum", "exp", "log"}


def _state():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    cfg = {
        "enable": enable,
        "level": level,
        "dtype": to_jax_dtype(dtype),
        "white": WHITE_LIST | set(custom_white_list or ()),
        "black": BLACK_LIST | set(custom_black_list or ()),
    }
    _state().append(cfg)
    try:
        yield
    finally:
        _state().pop()


amp_guard = auto_cast


def get_amp_policy():
    s = _state()
    return s[-1] if s else None


def amp_dtype():
    """Compute dtype under the active autocast policy (None if disabled)."""
    p = get_amp_policy()
    if p and p["enable"]:
        return p["dtype"]
    return None


def amp_cast(x, op_name="matmul"):
    """Cast `x` per the active policy for op `op_name` (O1 per-op casting)."""
    p = get_amp_policy()
    if not p or not p["enable"]:
        return x
    if op_name in p["black"]:
        target = jnp.float32
    elif op_name in p["white"] or p["level"] == "O2":
        target = p["dtype"]
    else:
        return x
    return jax.tree_util.tree_map(
        lambda t: t.astype(target) if hasattr(t, "dtype") and is_floating(t.dtype) else t, x)


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Cast model params to `dtype` (level O2); optimizers keep fp32 masters."""
    dt = to_jax_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            for _, p in m.named_parameters():
                # keep norm-style small vectors in fp32 for numerics
                if is_floating(p.value.dtype):
                    p.value = p.value.astype(dt)
    if optimizers is None:
        return models if single else model_list
    opt_single = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if opt_single else list(optimizers)
    for o in opt_list:
        o.multi_precision = True
    return (models if single else model_list,
            optimizers if opt_single else opt_list)


class GradScaler:
    """Dynamic loss scaling (ref: python/paddle/amp/grad_scaler.py).

    Functional usage inside jit:
        scaled = scaler.scale(loss)
        ... grads of scaled loss ...
        grads, found_inf = scaler.unscale(grads)
        new_sstate = scaler.update_state(sstate, found_inf)
    Eager usage mirrors the reference (`scale`, `step`-less minimize flow).
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self.enable = enable
        self.init_loss_scaling = init_loss_scaling
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n = decr_every_n_nan_or_inf
        self.dynamic = use_dynamic_loss_scaling
        self._scale = jnp.asarray(init_loss_scaling, jnp.float32)
        self._good_steps = 0

    def init_state(self):
        return {"scale": jnp.asarray(self.init_loss_scaling, jnp.float32),
                "good_steps": jnp.zeros((), jnp.int32)}

    def scale(self, loss, state=None):
        if not self.enable:
            return loss
        s = state["scale"] if state is not None else self._scale
        return loss * s

    def unscale(self, grads, state=None):
        if not self.enable:
            return grads, jnp.zeros((), jnp.bool_)
        s = state["scale"] if state is not None else self._scale
        inv = 1.0 / s
        un = jax.tree_util.tree_map(lambda g: g * inv, grads)
        leaves = jax.tree_util.tree_leaves(un)
        found_inf = jnp.any(jnp.stack([jnp.any(~jnp.isfinite(g)) for g in leaves]))
        return un, found_inf

    def update_state(self, state, found_inf):
        if not self.dynamic:
            return state
        good = jnp.where(found_inf, 0, state["good_steps"] + 1)
        grow = good >= self.incr_every_n_steps
        scale = jnp.where(found_inf, state["scale"] * self.decr_ratio,
                          jnp.where(grow, state["scale"] * self.incr_ratio,
                                    state["scale"]))
        scale = jnp.clip(scale, 1.0, 2.0 ** 31)
        good = jnp.where(grow, 0, good)
        return {"scale": scale, "good_steps": good}

    # eager parity
    def is_enable(self):
        return self.enable

    def get_loss_scaling(self):
        return float(self._scale)
