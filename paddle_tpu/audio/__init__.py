"""paddle.audio parity — spectral feature layers over jnp.fft.

Reference: python/paddle/audio/{functional,features} — get_window,
mel/fbank/dct math and the Spectrogram/MelSpectrogram/LogMelSpectrogram/
MFCC layers. Everything lowers to XLA (rfft + matmuls) — TPU-friendly
static shapes throughout.
"""

from paddle_tpu.audio import features  # noqa: F401
from paddle_tpu.audio import functional  # noqa: F401
from paddle_tpu.audio.features import (  # noqa: F401
    LogMelSpectrogram,
    MelSpectrogram,
    MFCC,
    Spectrogram,
)
