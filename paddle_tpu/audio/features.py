"""paddle.audio.features parity — feature-extraction Layers."""

import jax
import jax.numpy as jnp

from paddle_tpu.audio import functional as AF
from paddle_tpu.nn.layer import Layer


def _resolve_dtype(dtype):
    dt = jnp.dtype(dtype)
    if dt == jnp.float64 and not jax.config.jax_enable_x64:
        raise ValueError(
            "dtype='float64' needs jax_enable_x64; enable it or use "
            "'float32'")
    return dt


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.cfg = dict(n_fft=n_fft, hop_length=hop_length,
                        win_length=win_length, window=window, power=power,
                        center=center, pad_mode=pad_mode)
        self._dtype = _resolve_dtype(dtype)

    def forward(self, x):
        return AF.spectrogram(x.astype(self._dtype),
                              **self.cfg).astype(self._dtype)


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype=dtype)
        self.register_buffer("fbank", AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk,
            norm).astype(_resolve_dtype(dtype)))

    def forward(self, x):
        s = self.spectrogram(x)          # (..., n_freqs, n_frames)
        return jnp.einsum("mf,...ft->...mt", self.fbank, s)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm, dtype=dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db, dtype=dtype)
        self.register_buffer("dct", AF.create_dct(
            n_mfcc, n_mels).astype(_resolve_dtype(dtype)))

    def forward(self, x):
        lm = self.logmel(x)              # (..., n_mels, n_frames)
        return jnp.einsum("mk,...mt->...kt", self.dct, lm)
