"""paddle.audio.functional parity (window/mel/dct math)."""

import math

import numpy as np

import jax.numpy as jnp


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    # slaney scale (librosa/paddle default)
    freq = np.asarray(freq, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    safe = np.maximum(freq, 1e-10)  # avoid log(0) in the unused branch
    return np.where(freq >= min_log_hz,
                    min_log_mel + np.log(safe / min_log_hz) / logstep, mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    mel = np.asarray(mel, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(mel >= min_log_mel,
                    min_log_hz * np.exp(logstep * (mel - min_log_mel)), freqs)


def get_window(window, win_length, fftbins=True):
    n = win_length
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(n) / (n if fftbins
                                                           else n - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * np.arange(n) / (n if fftbins
                                                             else n - 1))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    elif window == "blackman":
        m = n if fftbins else n - 1
        w = (0.42 - 0.5 * np.cos(2 * np.pi * np.arange(n) / m)
             + 0.08 * np.cos(4 * np.pi * np.arange(n) / m))
    else:
        raise ValueError(f"unsupported window {window!r}")
    return jnp.asarray(w, jnp.float32)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """(n_mels, n_fft//2 + 1) triangular mel filter bank."""
    f_max = f_max if f_max is not None else sr / 2.0
    n_freqs = n_fft // 2 + 1
    fft_freqs = np.linspace(0.0, sr / 2.0, n_freqs)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_freqs))
    for m in range(n_mels):
        lo, ctr, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[m] = np.maximum(0.0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return jnp.asarray(fb, jnp.float32)


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """(n_mels, n_mfcc) DCT-II basis (reference create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)
    basis = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(2.0)
        basis *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return jnp.asarray(basis, jnp.float32)


def frame(x, frame_length, hop_length, center=True, pad_mode="reflect"):
    """(..., T) → (..., n_frames, frame_length) overlapping frames."""
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(frame_length // 2,
                                          frame_length // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    t = x.shape[-1]
    n_frames = 1 + (t - frame_length) // hop_length
    if n_frames <= 0:
        raise ValueError(
            f"signal length {t} (after centering) is shorter than "
            f"frame_length {frame_length} — no frames to extract")
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    return jnp.take(x, idx, axis=-1)


def stft(x, n_fft=512, hop_length=None, win_length=None, window="hann",
         center=True, pad_mode="reflect"):
    """(..., T) → complex (..., n_fft//2+1, n_frames)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = get_window(window, win_length)
    if win_length < n_fft:  # center-pad window to n_fft
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
    frames = frame(x, n_fft, hop_length, center, pad_mode)
    spec = jnp.fft.rfft(frames * w, axis=-1)
    return jnp.swapaxes(spec, -1, -2)


def spectrogram(x, n_fft=512, hop_length=None, win_length=None,
                window="hann", power=2.0, center=True, pad_mode="reflect"):
    s = jnp.abs(stft(x, n_fft, hop_length, win_length, window, center,
                     pad_mode))
    return s if power == 1.0 else jnp.power(s, power)


def power_to_db(s, ref_value=1.0, amin=1e-10, top_db=80.0):
    log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec
