"""Data pipeline (ref: python/paddle/io/ — Dataset, IterableDataset, DataLoader,
BatchSampler, DistributedBatchSampler; C++ reader ops paddle/fluid/operators/reader/).

TPU-first: the loader produces host numpy batches; device transfer happens once
per step at the jit boundary (or via `device_put` with a sharding for multi-chip
input pipelines). Background prefetching uses a thread pool — on TPU the input
pipeline only has to beat the step time, and XLA overlaps the H2D copy.
"""

import itertools
import math
import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from paddle_tpu.core import rng as _rng


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not indexable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [np.asarray(t) for t in tensors]
        assert all(len(t) == len(self.tensors[0]) for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self._epoch = 0

    def __iter__(self):
        n = len(self.data_source)
        # fold in an epoch counter so each pass reshuffles even when nothing
        # draws from the global generator between epochs
        self._epoch += 1
        rng = np.random.default_rng(
            (_rng.get_rng_state()[0], _rng.get_rng_state()[1], self._epoch))
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler:
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the sample space across data-parallel ranks
    (ref: python/paddle/io/dataloader/batch_sampler.py)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from paddle_tpu.parallel import env as penv
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else penv.get_world_size()
        self.local_rank = rank if rank is not None else penv.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        indices = list(range(len(self.dataset)))
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            rng.shuffle(indices)
        indices += indices[: self.total_size - len(indices)]
        local = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return np.stack([np.asarray(b) for b in batch])


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def _gen(self) -> Iterator:
        if self._iterable:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch or (len(batch) < self.batch_size and self.drop_last):
                    return
                yield self.collate_fn(batch)
                if len(batch) < self.batch_size:
                    return
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._gen()
            return
        # threaded prefetch (the C++ buffered-reader analog)
        q: "queue.Queue" = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        stop = object()

        def producer():
            try:
                for item in self._gen():
                    q.put(item)
                q.put(stop)
            except BaseException as e:  # surface dataset errors to the consumer
                q.put(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is stop:
                break
            if isinstance(item, BaseException):
                raise item
            yield item

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)


# ---- dataset combinators (reference: python/paddle/io/dataset.py) ----------

class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self._cum = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self._cum.append(total)

    def __len__(self):
        return self._cum[-1] if self._cum else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        import bisect
        ds = bisect.bisect_right(self._cum, idx)
        prev = self._cum[ds - 1] if ds else 0
        return self.datasets[ds][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __len__(self):
        return len(self.indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]


def random_split(dataset, lengths, generator=None):
    """Split into non-overlapping subsets (reference random_split).

    `lengths` may be absolute sizes or fractions summing to 1."""
    import numpy as _np
    n = len(dataset)
    if all(0 < float(l) < 1 for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        sizes = [int(math.floor(n * float(l))) for l in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
    else:
        sizes = [int(l) for l in lengths]
        if sum(sizes) != n:
            raise ValueError(
                f"sum of lengths {sum(sizes)} != dataset size {n}")
    rng = generator if generator is not None else _np.random.RandomState()
    perm = rng.permutation(n)
    out, ofs = [], 0
    for s in sizes:
        out.append(Subset(dataset, perm[ofs:ofs + s].tolist()))
        ofs += s
    return out


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__()
        self.indices = list(indices)
        self.generator = generator

    def __iter__(self):
        import numpy as _np
        rng = self.generator or _np.random.RandomState()
        return iter([self.indices[i]
                     for i in rng.permutation(len(self.indices))])

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True,
                 generator=None):
        super().__init__()
        import numpy as _np
        self.weights = _np.asarray(weights, _np.float64)
        if (self.weights < 0).any():
            raise ValueError("weights must be non-negative")
        self.num_samples = num_samples
        self.replacement = replacement
        self.generator = generator

    def __iter__(self):
        import numpy as _np
        rng = self.generator or _np.random.RandomState()
        p = self.weights / self.weights.sum()
        idx = rng.choice(len(self.weights), size=self.num_samples,
                         replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples
