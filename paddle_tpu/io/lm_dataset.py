"""LM pretrain data: packed fixed-length rows over tokenized documents.

The packing/shuffle/gather hot loops run in native code
(csrc/data_pipeline.cc via io.native); this module is the Dataset-level
veneer used by the pretrain configs (BASELINE GPT-2/Llama)."""

from typing import Dict, Optional

import numpy as np

from paddle_tpu.io import Dataset
from paddle_tpu.io import native


class PackedTokenDataset(Dataset):
    """Documents → eos-joined packed rows of seq_len+1 tokens; __getitem__
    yields {'input': (seq_len,), 'labels': (seq_len,)} shifted pairs."""

    def __init__(self, tokens, doc_offsets=None, seq_len: int = 1024,
                 eos_id: int = 0, shuffle_docs: bool = False, seed: int = 0):
        tokens = np.ascontiguousarray(tokens, dtype=np.int32)
        if doc_offsets is None:
            doc_offsets = np.asarray([0, tokens.size], dtype=np.int64)
        order = None
        if shuffle_docs:
            order = native.shuffle_indices(len(doc_offsets) - 1, seed)
        self.rows = native.pack_documents(tokens, doc_offsets, seq_len + 1,
                                          eos_id, doc_order=order)
        self.seq_len = seq_len

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, idx) -> Dict[str, np.ndarray]:
        row = self.rows[idx]
        return {"input": row[:-1], "labels": row[1:]}

    def epoch_batches(self, batch_size: int, seed: int = 0,
                      drop_last: bool = True):
        """Fast path: native shuffle + native row gather, no per-sample
        Python loop (the C++ buffered-reader analog for in-memory data)."""
        idx = native.shuffle_indices(len(self.rows), seed)
        n = (len(idx) // batch_size) * batch_size if drop_last else len(idx)
        for i in range(0, n, batch_size):
            batch = native.gather_rows(self.rows, idx[i:i + batch_size])
            yield {"input": batch[:, :-1], "labels": batch[:, 1:]}


def from_token_file(path: str, seq_len: int = 1024, eos_id: int = 0,
                    dtype=np.uint16) -> PackedTokenDataset:
    """Memory-mapped flat token file (GPT-2-style .bin) → packed dataset."""
    toks = np.memmap(path, dtype=dtype, mode="r")
    return PackedTokenDataset(np.asarray(toks, dtype=np.int32),
                              seq_len=seq_len, eos_id=eos_id)
