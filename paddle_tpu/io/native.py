"""ctypes bridge to the native data-pipeline kernels (csrc/data_pipeline.cc).

Builds the shared library on first use with g++ (cached next to csrc/);
every entry point has a NumPy fallback so the package works without a
toolchain. The reference's analogous native surface is the C++ reader-op /
shared-memory DataLoader stack (SURVEY.md §2.7-data)."""

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger("paddle_tpu.io.native")

_lock = threading.Lock()
_lib = None
_tried = False


def _csrc_dir():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "csrc")


def _build_and_load():
    src = os.path.join(_csrc_dir(), "data_pipeline.cc")
    out = os.path.join(_csrc_dir(), "libpaddle_tpu_data.so")
    if not os.path.exists(src):
        return None
    if (not os.path.exists(out)
            or os.path.getmtime(out) < os.path.getmtime(src)):
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", out, src]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as e:
            logger.warning("native data pipeline build failed (%s); "
                           "using NumPy fallbacks", e)
            return None
    lib = ctypes.CDLL(out)
    lib.shuffle_indices.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_uint64]
    lib.pack_documents.restype = ctypes.c_int64
    lib.pack_documents.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32]
    lib.gather_rows.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)]
    return lib


def get_lib():
    global _lib, _tried
    with _lock:
        if not _tried:
            _tried = True
            _lib = _build_and_load()
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def _i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def shuffle_indices(n: int, seed: int) -> np.ndarray:
    """Deterministic epoch-shuffled index permutation of [0, n)."""
    idx = np.arange(n, dtype=np.int64)
    lib = get_lib()
    if lib is not None:
        lib.shuffle_indices(_i64p(idx), n, np.uint64(seed))
        return idx
    rs = np.random.RandomState(np.uint32(seed & 0xFFFFFFFF))
    rs.shuffle(idx)
    return idx


def pack_documents(tokens: np.ndarray, doc_offsets: np.ndarray, row_len: int,
                   eos_id: int, doc_order: np.ndarray = None) -> np.ndarray:
    """Pack a concatenated token stream into (rows, row_len) int32 training
    rows with eos separators; documents split across row boundaries."""
    tokens = np.ascontiguousarray(tokens, dtype=np.int32)
    doc_offsets = np.ascontiguousarray(doc_offsets, dtype=np.int64)
    n_docs = len(doc_offsets) - 1
    total = int(tokens.size + n_docs)     # tokens + eos per doc
    rows = (total + row_len - 1) // row_len
    out = np.full((rows, row_len), eos_id, dtype=np.int32)
    lib = get_lib()
    if lib is not None:
        order_p = ctypes.POINTER(ctypes.c_int64)()
        if doc_order is not None:
            # keep the contiguous array alive while the pointer is in use
            doc_order = np.ascontiguousarray(doc_order, dtype=np.int64)
            order_p = _i64p(doc_order)
        written = lib.pack_documents(_i32p(tokens), _i64p(doc_offsets),
                                     n_docs, order_p, _i32p(out), rows,
                                     row_len, eos_id)
        return out[:written]
    # NumPy fallback
    order = doc_order if doc_order is not None else np.arange(n_docs)
    stream = []
    for d in order:
        stream.append(tokens[doc_offsets[d]:doc_offsets[d + 1]])
        stream.append(np.asarray([eos_id], np.int32))
    flat = np.concatenate(stream) if stream else np.zeros(0, np.int32)
    n_full = min(len(flat) // row_len, rows)
    out[:n_full] = flat[:n_full * row_len].reshape(n_full, row_len)
    rem = flat[n_full * row_len:]
    if len(rem) and n_full < rows:
        out[n_full, :len(rem)] = rem
        return out[:n_full + 1]
    return out[:n_full]


def gather_rows(tokens: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """tokens (N, row_len) int32, idx (b,) → (b, row_len) batch."""
    tokens = np.ascontiguousarray(tokens, dtype=np.int32)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    lib = get_lib()
    if lib is not None:
        out = np.empty((len(idx), tokens.shape[1]), dtype=np.int32)
        lib.gather_rows(_i32p(tokens), _i64p(idx), len(idx),
                        tokens.shape[1], _i32p(out))
        return out
    return tokens[idx]
