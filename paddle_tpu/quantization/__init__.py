"""Weight-only int8 quantization for inference.

Reference (SURVEY.md §2.2-fusion): the decode crown jewels ship int8
variants — `fused_multi_transformer_int8_op.cu`, and the python surface
`paddle.nn.quant.weight_only_linear` / `paddle.quantization`. Decode is
HBM-bandwidth bound (see examples/decode_bench.py): streaming int8
weights instead of bf16 halves the bytes/step, which is the single
biggest decode-throughput lever on TPU as on GPU.

TPU-native design: weights are stored as int8 + a per-output-channel
fp32 scale; the forward dequantizes `w = q.astype(bf16) * scale` right at
the matmul operand, which XLA fuses into the dot's operand load — HBM
traffic stays int8. No kernel is needed; the MXU consumes the dequantized
tiles from VMEM.

`quantize_model(model)` converts IN PLACE: every Linear-like sublayer
(plain, column-, row-, or sequence-parallel — anything with a 2-D
`weight` whose forward reads `self.weight`) gets its weight replaced by
(weight_q int8, weight_scale fp32) and a class-level `weight` property
that dequantizes on read. Class behavior (sharding constraints, bias,
gather/scatter) is preserved exactly; TP pspecs carry over to the int8
tensor.
"""

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer, Parameter


def quantize_weight_int8(w) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel (last dim) int8 quantization.

    w: (..., in, out) float → (int8 same shape, fp32 scale (out,))."""
    wf = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=tuple(range(wf.ndim - 1)))
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def weight_only_linear(x, weight_q, weight_scale, bias=None):
    """paddle.nn.quant.weight_only_linear parity (int8 path)."""
    w = weight_q.astype(x.dtype) * weight_scale.astype(x.dtype)
    y = x @ w
    if bias is not None:
        y = y + bias
    return y


_QUANT_CLASS_CACHE = {}


def _quantized_class(base, dequant_dtype):
    key = (base, jnp.dtype(dequant_dtype).name)
    cls = _QUANT_CLASS_CACHE.get(key)
    if cls is None:
        def _weight(self):
            q = self._parameters["weight_q"].value
            s = self._parameters["weight_scale"].value
            return q.astype(dequant_dtype) * s.astype(dequant_dtype)

        cls = type(f"Int8{base.__name__}", (base,),
                   {"weight": property(_weight),
                    "_is_weight_only_int8": True})
        _QUANT_CLASS_CACHE[key] = cls
    return cls


def _quantize_layer(layer: Layer, dequant_dtype):
    w = layer._parameters.pop("weight")
    q, scale = quantize_weight_int8(w.value)
    qp = Parameter(q, trainable=False)
    sp = Parameter(scale, trainable=False)
    # carry the TP sharding onto the int8 tensor; the per-out-channel scale
    # is sharded iff the out (last) dim of the weight was
    pspec = getattr(w, "pspec", None)
    if pspec is not None:
        qp.pspec = pspec
        qp.is_distributed = getattr(w, "is_distributed", False)
        from jax.sharding import PartitionSpec as P
        out_axis = pspec[-1] if len(pspec) else None
        sp.pspec = P(out_axis)
        sp.is_distributed = qp.is_distributed
    layer._parameters["weight_q"] = qp
    layer._parameters["weight_scale"] = sp
    layer.__class__ = _quantized_class(type(layer), dequant_dtype)


def quantize_model(model: Layer, dequant_dtype=jnp.bfloat16,
                   include: Optional[Sequence[type]] = None,
                   exclude_names: Sequence[str] = ("embed",)) -> Layer:
    """In-place weight-only int8 conversion of every Linear-like sublayer.

    A sublayer qualifies when it has a 2-D `weight` parameter and is not
    name-matched by `exclude_names` (embeddings keep full precision — the
    gather reads one row, quantization saves nothing and costs accuracy).
    Returns the same model for chaining."""
    for name, sub in model.named_sublayers(include_self=True):
        if getattr(sub, "_is_weight_only_int8", False):
            continue
        w = sub._parameters.get("weight")
        if w is None or w.value.ndim != 2:
            continue
        if include is not None and not isinstance(sub, tuple(include)):
            continue
        if any(t in name.lower() or t in type(sub).__name__.lower()
               for t in exclude_names):
            continue
        _quantize_layer(sub, dequant_dtype)
    return model


def quantized_state(model: Layer):
    """All named parameters (incl. the non-trainable int8/scale tensors) —
    pass as `state=` to functional_call / inference.generate."""
    return {n: p.value for n, p in model.named_parameters()}
