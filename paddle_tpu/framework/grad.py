"""Autograd + jit veneer.

The reference's eager autograd engine (egr::Backward, GradNode graph —
paddle/fluid/eager/backward.cc) is subsumed by jax.grad: the backward graph is
built by tracing, not taped at runtime. This module provides the user-facing
helpers that make the functional style feel like the reference:

* ``paddle_tpu.grad(fn)`` / ``value_and_grad`` — jax passthroughs.
* ``paddle_tpu.jit.to_static(fn)`` — jax.jit with donate/static conveniences (the
  analog of @to_static: trace once, run compiled; dy2static's AST rewriting is
  unnecessary because jax traces Python directly, with lax.cond/scan for
  data-dependent control flow).
* ``value_and_grad_layer(layer, loss_fn)`` — grads of a Layer's trainable
  state via the functional bridge.
* ``no_grad`` — stop-gradient context parity (functional code simply doesn't
  differentiate; this exists for API compatibility and wraps jax.lax.stop_gradient
  on request).
"""

import contextlib
import functools

import jax

grad = jax.grad
value_and_grad = jax.value_and_grad


def jit(fn=None, *, static_argnums=None, static_argnames=None, donate_argnums=None):
    if fn is None:
        return functools.partial(jit, static_argnums=static_argnums,
                                 static_argnames=static_argnames,
                                 donate_argnums=donate_argnums)
    return jax.jit(fn, static_argnums=static_argnums,
                   static_argnames=static_argnames,
                   donate_argnums=donate_argnums or ())


to_static = jit  # @paddle.jit.to_static parity: trace-and-compile


@contextlib.contextmanager
def no_grad():
    yield


def stop_gradient(x):
    return jax.lax.stop_gradient(x)


def value_and_grad_layer(layer, loss_fn, has_aux=False):
    """Return f(state, *args) -> ((loss, aux?), grads) over `layer`'s state.

    `loss_fn(outputs, *args) -> loss` is applied to layer(*inputs).
    """
    from paddle_tpu.nn.layer import functional_call

    def wrapped(state, inputs, *loss_args, rngs=None):
        def inner(s):
            out = functional_call(layer, s, *inputs, rngs=rngs)
            return loss_fn(out, *loss_args)
        return jax.value_and_grad(inner, has_aux=has_aux)(state)

    return wrapped
