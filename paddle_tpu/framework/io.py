"""Checkpoint I/O: paddle.save / paddle.load parity.

Ref: python/paddle/framework/io.py — pickled state_dict trees. Here arrays are
stored as numpy inside a pickle (protocol 4, >4 GB safe); sharding-aware
distributed checkpointing (the Orbax path, with resharding-on-load) lives in
paddle_tpu/parallel/checkpoint.py.
"""

import os
import pickle

import jax
import numpy as np


def _to_host(obj):
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    return obj


def save(obj, path, protocol=4):
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_host(obj), f, protocol=protocol)


def load(path, return_numpy=False):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        return obj

    def to_jax(o):
        if isinstance(o, np.ndarray):
            import jax.numpy as jnp
            return jnp.asarray(o)
        if isinstance(o, dict):
            return {k: to_jax(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(to_jax(v) for v in o)
        return o

    return to_jax(obj)
