"""Memory telemetry: live HBM, device allocator stats, executable memory.

Three sources, all surfaced as gauges in the metrics registry:

* ``live_array_bytes()`` — sum over ``jax.live_arrays()`` (host view of
  every live jax.Array buffer; works on every backend).
* ``device_memory_stats()`` — the device allocator's own counters
  (``bytes_in_use`` / ``peak_bytes_in_use``) where the backend exposes
  them (TPU does; CPU typically returns nothing).
* ``record_executable_memory(ma)`` — XLA's compiled-module accounting
  (``compiled.memory_analysis()``: argument/temp/output bytes), the
  number scale_report's feasibility tables are built on.
"""

from typing import Dict, Optional

from paddle_tpu.observability.registry import registry as default_registry

__all__ = ["live_array_bytes", "device_memory_stats", "record_memory",
           "record_executable_memory", "memory_snapshot"]


def live_array_bytes() -> int:
    """Total bytes of live jax.Arrays (0 if the runtime can't enumerate)."""
    import jax

    try:
        return sum(int(a.nbytes) for a in jax.live_arrays())
    except Exception:
        return 0


def device_memory_stats(device=None) -> Dict[str, int]:
    """The backend allocator's stats for `device` (default: device 0);
    {} when the backend doesn't expose them (e.g. CPU)."""
    import jax

    try:
        dev = device or jax.devices()[0]
        stats = dev.memory_stats()
        return dict(stats) if stats else {}
    except Exception:
        return {}


def memory_snapshot(device=None) -> Dict[str, int]:
    """One dict joining both host-side and allocator views."""
    snap = {"live_array_bytes": live_array_bytes()}
    stats = device_memory_stats(device)
    for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if k in stats:
            snap[k] = int(stats[k])
    return snap


def record_memory(registry=None, device=None, **labels) -> Dict[str, int]:
    """Gauge the current memory snapshot into `registry` (default: the
    process-wide one) as ``memory.<key>``; returns the snapshot."""
    reg = registry or default_registry()
    snap = memory_snapshot(device)
    for k, v in snap.items():
        reg.gauge(f"memory.{k}", **labels).set(v)
    return snap


def record_executable_memory(ma, registry=None, name: str = "",
                             **labels) -> Optional[Dict[str, int]]:
    """Gauge a compiled executable's memory_analysis() into the registry
    as ``executable.{argument,temp,output}_bytes`` (labelled name=...).
    `ma` is `compiled.memory_analysis()` (or the compiled object itself,
    in which case memory_analysis() is called here)."""
    reg = registry or default_registry()
    if hasattr(ma, "memory_analysis"):
        try:
            ma = ma.memory_analysis()
        except Exception:
            return None
    out = {}
    for field, key in (("argument_size_in_bytes", "argument_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("output_size_in_bytes", "output_bytes")):
        v = getattr(ma, field, None)
        if v is not None:
            out[key] = int(v)
            reg.gauge(f"executable.{key}", name=name, **labels).set(int(v))
    return out or None
