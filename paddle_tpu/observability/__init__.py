"""paddle_tpu.observability — unified telemetry across the stack.

One subsystem, four pieces (docs/OBSERVABILITY.md has the full story):

* **Metrics registry** (`registry.py`): process-wide counters / gauges /
  fixed-bucket histograms, allocation-free on the hot path, exported as
  JSONL or Prometheus text. Subsumes and backs `profiler.MetricsLogger`
  / `profiler.StepTimer`.
* **Request tracing** (`tracing.py`): attach a `Tracer` and
  `inference.generate` / `StackedLlamaDecoder.generate` emit per-request
  spans — prefill, per-chunk decode — with TTFT/TPOT/tokens-per-sec and
  KV-cache bytes/dtype, nested in `jax.profiler.TraceAnnotation` so they
  land in xplane captures. No tracer attached → the single-dispatch
  decode path runs untouched (<1% overhead: one global read per call).
* **Schemas** (`schema.py`): the shared `paddle_tpu.bench/v1` BENCH
  record all benches emit + span validation.
* **Memory telemetry** (`memory.py`): live-HBM / allocator stats /
  compiled-executable accounting as registry gauges.
* **SLO quantiles** (`slo.py`): DDSketch-style streaming quantile
  sketch (`registry().sketch(...)`) + `SLOReport` folding per-request
  TTFT/TPOT into p50/p95/p99 and goodput-under-SLO bench fields.
* **Flight recorder** (`flight.py`): fixed-size ring of per-step
  serving-engine events, auto-dumped to JSONL at the resilience seams
  (fired fault / `PoolExhausted` / deadline retirement) for
  postmortems.
* **Timeline export** (`timeline.py`): folds spans + flight rings +
  the router journal into Chrome trace-event JSON (Perfetto-loadable)
  with per-replica process tracks and `trace_id`-keyed flow arrows —
  plus the trace-continuity checker the chaos harness gates on.

Roofline attribution lives with the xplane parser:
`paddle_tpu.profiler.roofline_report(log_dir, plan)`.
"""

from paddle_tpu.observability.registry import (   # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, DEFAULT_BUCKETS,
    registry, set_default_labels,
)
from paddle_tpu.observability.tracing import (    # noqa: F401
    Span, Tracer, attach, detach, active_tracer, trace, run_traced_decode,
)
from paddle_tpu.observability.schema import (     # noqa: F401
    BENCH_SCHEMA, bench_record, validate_bench, validate_spans,
    validate_roofline_plan,
)
from paddle_tpu.observability.slo import (        # noqa: F401
    QuantileSketch, SLOReport, BurnRateWatchdog,
)
from paddle_tpu.observability.flight import (     # noqa: F401
    FLIGHT_SCHEMA, FlightRecorder,
)
from paddle_tpu.observability.timeline import (   # noqa: F401
    build_timeline, write_timeline, verify_trace_continuity, clock_anchor,
)
from paddle_tpu.observability import flight       # noqa: F401
from paddle_tpu.observability import memory       # noqa: F401
from paddle_tpu.observability import schema       # noqa: F401
from paddle_tpu.observability import slo          # noqa: F401
from paddle_tpu.observability import timeline     # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DEFAULT_BUCKETS",
    "registry", "set_default_labels",
    "Span", "Tracer", "attach", "detach", "active_tracer", "trace",
    "run_traced_decode",
    "BENCH_SCHEMA", "bench_record", "validate_bench", "validate_spans",
    "validate_roofline_plan",
    "QuantileSketch", "SLOReport", "BurnRateWatchdog",
    "FLIGHT_SCHEMA", "FlightRecorder",
    "build_timeline", "write_timeline", "verify_trace_continuity",
    "clock_anchor",
    "flight", "memory", "schema", "slo", "timeline",
]
